"""OpenAI-compatible HTTP frontend + engine-facing RPC endpoints.

Parity: reference `http_service/service.cpp` (SURVEY.md §2.2, §3.2) and
`rpc_service/service.cpp` (§2.3, §3.3):

HTTP app (client-facing, reference routes in `master.cpp:71-76`):
- POST /v1/completions, /v1/chat/completions — parse body → Request with
  service id `method-threadid-shortuuid` → `Scheduler::schedule` → forward
  the **enriched** body (service_request_id, source_service_addr, token_ids,
  routing) to the chosen prefill instance fire-and-forget
  (`service.cpp:222-260,407-415,485-493`) → stream SSE back as Generations
  arrive.
- GET /v1/models — proxied/aggregated from instance metadata
  (`service.cpp:317-357`).
- POST /v1/embeddings — "not support" (`service.cpp:500-517`).
- GET /metrics — Prometheus text (reference leaves this TODO-empty,
  `service.cpp:526-532`; we implement it).
- GET /hello, GET /health.

RPC app (engine-facing, reference `XllmRpcService`):
- POST /rpc/heartbeat → `Scheduler::handle_instance_heartbeat`.
- POST /rpc/generations → batched deltas → `Scheduler::handle_generation`
  (`rpc_service/service.cpp:149-215`).
- GET /rpc/hello, /rpc/instance_info, /rpc/static_prefill_list,
  /rpc/static_decode_list (P/D peer discovery for engines).
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import time
from typing import Any, Optional

import aiohttp
from aiohttp import web

from ..common import flightrecorder
from ..common import native as _native
from ..common.flightrecorder import RECORDER
from ..common.hotpath import CPU_ATTR, HOTPATH
from ..common.metrics import (
    ADMISSION_PENDING_REQUESTS,
    AUTOSCALER_LAST_DECISION_AGE_SECONDS,
    BROWNOUT_ACTIVE,
    FLEET_SIZE,
    HANDOFF_JOURNAL_REPLAYS_TOTAL,
    HANDOFF_SERVED_TOTAL,
    KVCACHE_FRAME_LOG_SEQ,
    LOADINFO_AGE_SECONDS,
    LOADINFO_MAX_AGE_SECONDS,
    LOADINFO_STALE_INSTANCES,
    REGISTRY,
    REQUESTS_CANCELLED_TOTAL,
    RETRY_BUDGET_TOKENS,
    ROUTING_SNAPSHOT_AGE_SECONDS,
    SERVER_REQUEST_IN_TOTAL,
    TELEMETRY_GENS_RELAYED_TOTAL,
    relabel_prometheus_text,
)
from ..common.request import Request, RequestOutput, SamplingParams
from ..common.slo import SLO_MONITOR
from ..common import tracing
from ..common.tracing import TRACER, TraceContext, merge_fleet_spans, span_tree
from ..common.types import InstanceType
from ..multimaster.handoff import DeltaJournal, HandoffRelay
from ..overload import (
    ABS_DEADLINE_HEADER,
    ADMISSION,
    BROWNOUT,
    RETRY_BUDGET,
    deadline_expired,
    parse_deadline_ms,
    parse_priority,
)
from ..overload.deadline import remaining_ms
from ..profiling import (PROFILER, aggregate_critical_paths, critical_path,
                         handle_admin_profile, parse_folded, summarize_stacks)
from ..rpc import wire
from ..scheduler.scheduler import Scheduler
from ..utils import generate_service_request_id, get_logger, short_uuid
from .connection import AioConnection
from .request_tracer import RequestTracer

logger = get_logger(__name__)

# Preserialized SSE frame pieces: the emit loop is per-delta hot, so the
# constant bytes are built once, and delta JSON uses compact separators
# (identical parse, fewer bytes, faster dumps).
_DATA_PREFIX = b"data: "
_FRAME_SEP = b"\n\n"
_DONE_FRAME = b"data: [DONE]\n\n"
_COMPACT = (",", ":")


def _num(body: dict[str, Any], key: str, default, cast):
    """OpenAI clients serialize unset optionals as explicit null; treat null
    as default instead of crashing in int()/float()."""
    v = body.get(key)
    return cast(v) if v is not None else default


def _parse_sampling(body: dict[str, Any]) -> SamplingParams:
    sp = SamplingParams()
    sp.max_tokens = _num(body, "max_tokens",
                         _num(body, "max_completion_tokens", 16, int), int)
    sp.temperature = _num(body, "temperature", 1.0, float)
    sp.top_p = _num(body, "top_p", 1.0, float)
    sp.top_k = _num(body, "top_k", -1, int)
    sp.n = _num(body, "n", 1, int)
    sp.frequency_penalty = _num(body, "frequency_penalty", 0.0, float)
    sp.presence_penalty = _num(body, "presence_penalty", 0.0, float)
    sp.repetition_penalty = _num(body, "repetition_penalty", 1.0, float)
    stop = body.get("stop")
    if isinstance(stop, str):
        sp.stop = [stop]
    elif isinstance(stop, list):
        sp.stop = [str(s) for s in stop]
    sp.stop_token_ids = list(body.get("stop_token_ids", ()))
    if body.get("seed") is not None:
        sp.seed = int(body["seed"])
    lp = body.get("logprobs")
    if isinstance(lp, bool):
        sp.logprobs = lp
        sp.top_logprobs = int(body.get("top_logprobs", 0) or 0)
    elif isinstance(lp, int):  # completions-style int logprobs
        sp.logprobs = lp > 0
        sp.top_logprobs = lp
    sp.ignore_eos = bool(body.get("ignore_eos", False))
    sp.echo = bool(body.get("echo", False))
    return sp


def _cast_bool(v: Any) -> bool:
    """Admin-config boolean caster: JSON true/false or the string forms —
    bool("false") is True, which would silently invert an operator's
    intent."""
    if isinstance(v, bool):
        return v
    if isinstance(v, (int, float)) and v in (0, 1):
        return bool(v)
    if isinstance(v, str) and v.lower() in ("true", "false", "1", "0"):
        return v.lower() in ("true", "1")
    raise ValueError(f"not a boolean: {v!r}")


def _error_response(code: int, message: str, etype: str = "invalid_request_error") -> web.Response:
    return web.json_response(
        {"error": {"message": message, "type": etype, "code": code}},
        status=code)


class XllmHttpService:
    """Both aiohttp applications + forwarding client."""

    def __init__(self, scheduler: Scheduler, tracer: Optional[RequestTracer] = None):
        self.scheduler = scheduler
        self.opts = scheduler._opts
        self.tracer = tracer or RequestTracer(self.opts.trace_dir,
                                              self.opts.enable_request_trace)
        # Span tracing: ring buffer per options; finished spans mirrored
        # into the RequestTracer JSONL when request tracing is on.
        TRACER.configure(
            enabled=self.opts.enable_tracing,
            capacity=self.opts.trace_span_capacity,
            mirror=self._mirror_span if self.tracer.enabled else None,
            sample_rate=self.opts.trace_sample_rate)
        # SLO burn-rate monitor + anomaly flight recorder (fleet
        # observability plane, docs/observability.md). The recorder's
        # context provider captures this frontend's control-plane view
        # into every anomaly bundle.
        SLO_MONITOR.configure(
            ttft_ms=self.opts.slo_ttft_ms, tpot_ms=self.opts.slo_tpot_ms,
            budget=self.opts.slo_error_budget,
            fast_s=self.opts.slo_fast_window_s,
            slow_s=self.opts.slo_slow_window_s,
            alert=self.opts.slo_burn_alert)
        RECORDER.configure(capacity=self.opts.flightrecorder_capacity,
                           directory=self.opts.flightrecorder_dir)
        RECORDER.add_context_provider("service", self._anomaly_context)
        # Native-core verdict in every anomaly bundle: a process quietly
        # running degraded pure-Python (missing .so, failed parity
        # self-test, XLLM_NATIVE=0) is exactly the asymmetry a fleet
        # perf anomaly investigation needs to see first.
        RECORDER.add_context_provider("native", _native.status)
        # Continuous profiler (profiling/sampler.py): always-on sampling
        # at profile_hz (0 disables), refcounted — an in-process engine
        # agent shares the same process-global sampler. The profiler
        # registers its own flight-recorder context provider, so every
        # anomaly bundle carries the last-window profile.
        PROFILER.configure(hz=self.opts.profile_hz,
                           window_s=self.opts.profile_window_s,
                           max_stacks=self.opts.profile_max_stacks,
                           max_depth=self.opts.profile_max_depth)
        PROFILER.start()
        # Overload-hardening plane (overload/, docs/robustness.md):
        # admission gate, brownout state, global retry budget. Ticked by
        # the scheduler's sync loop; enforced on the request paths here.
        ADMISSION.configure(
            per_instance_limit=self.opts.admission_max_inflight_per_instance,
            batch_watermark=self.opts.admission_batch_watermark,
            retry_after_s=self.opts.admission_retry_after_s)
        BROWNOUT.configure(
            enabled=self.opts.brownout_enabled,
            batch_max_tokens=self.opts.brownout_batch_max_tokens,
            recover_ticks=self.opts.brownout_recover_ticks,
            trace_sample_rate=self.opts.brownout_trace_sample_rate,
            restore_rate_fn=lambda: self.opts.trace_sample_rate)
        RETRY_BUDGET.configure(ratio=self.opts.retry_budget_ratio,
                               cap=self.opts.retry_budget_cap)
        # /metrics/fleet TTL cache: (monotonic deadline, rendered text).
        self._fleet_metrics_cache: Optional[tuple[float, str]] = None
        self._client: Optional[aiohttp.ClientSession] = None
        # Fleet fan-out concurrency bound (asyncio primitives bind their
        # loop lazily on first await, so construction here is safe).
        self._fleet_sem = asyncio.Semaphore(  # lock-order: 830
            max(1, self.opts.fleet_scrape_concurrency))
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        # The event loop keeps only weak refs to tasks; hold forward tasks
        # here so they can't be garbage-collected mid-flight.
        self._forward_tasks: set[asyncio.Task] = set()
        # Multi-master: owner-forward path for the minority of requests
        # this frontend accepts but does not own (multimaster/handoff.py),
        # plus the owner-side delta journal a relay reconnect replays
        # from (exact dedup — no pipeline re-run under sampling).
        self._journal = DeltaJournal(
            grace_s=self.opts.handoff_journal_grace_s)
        self._relay = HandoffRelay(
            scheduler.ownership,
            max_attempts=self.opts.handoff_max_attempts,
            stall_timeout_s=self.opts.handoff_stall_timeout_s,
            same_owner_retry=self._journal.enabled)

    # ------------------------------------------------------------- HTTP app
    def build_http_app(self) -> web.Application:
        app = web.Application(middlewares=[self._readiness_middleware])
        app.router.add_post("/v1/completions", self.handle_completions)
        app.router.add_post("/v1/chat/completions", self.handle_chat)
        app.router.add_post("/v1/messages", self.handle_messages)
        app.router.add_post("/v1/embeddings", self.handle_embeddings)
        app.router.add_get("/v1/models", self.handle_models)
        app.router.add_get("/metrics", self.handle_metrics)
        app.router.add_get("/hello", self.handle_hello)
        app.router.add_get("/health", self.handle_hello)
        app.router.add_get("/admin/config", self.handle_get_config)
        app.router.add_post("/admin/config", self.handle_set_config)
        app.router.add_get("/admin/planner", self.handle_planner)
        app.router.add_get("/admin/autoscaler", self.handle_autoscaler)
        app.router.add_get("/admin/coordination", self.handle_coordination)
        app.router.add_get("/admin/overload", self.handle_overload)
        app.router.add_get("/admin/hotpath", self.handle_hotpath)
        app.router.add_get("/admin/faults", self.handle_get_faults)
        app.router.add_post("/admin/faults", self.handle_set_faults)
        # Span-trace query surface. Default scope serves this process's
        # SpanStore (orchestration legs, failover re-dispatch attempts
        # correlated by trace_id); `?scope=fleet` fans out to every live
        # engine agent and peer frontend and merges the per-process span
        # rings into ONE tree.
        app.router.add_get("/admin/trace", self.handle_admin_trace)
        app.router.add_get("/admin/trace/recent",
                           self.handle_admin_trace_recent)
        # Fleet observability plane: merged fleet metrics, the SLO
        # burn-rate report, and the anomaly flight recorder.
        app.router.add_get("/metrics/fleet", self.handle_metrics_fleet)
        app.router.add_get("/admin/slo", self.handle_slo)
        app.router.add_get("/admin/flightrecorder/recent",
                           flightrecorder.handle_flightrecorder_recent)
        # Continuous-profiling plane: this process's folded stacks, or
        # `?scope=fleet` for the merged per-role view across every live
        # engine agent and peer frontend.
        app.router.add_get("/admin/profile", self.handle_admin_profile)
        app.on_startup.append(self._on_startup)
        app.on_cleanup.append(self._on_cleanup)
        return app

    def build_rpc_app(self) -> web.Application:
        app = web.Application()
        app.router.add_post("/rpc/heartbeat", self.handle_heartbeat)
        app.router.add_post("/rpc/generations", self.handle_generations)
        # Multiplexed engine telemetry session (ISSUE 15): ONE keepalive
        # connection per engine carries tagged hb/gens frames to the
        # engine's owning master; foreign-dest gens relay master->master.
        app.router.add_post("/rpc/telemetry", self.handle_telemetry)
        # Multi-master plane: owner-side ingest of relayed requests, and
        # the replica→master write-lease proxy for PD-role flip hints.
        app.router.add_post("/rpc/handoff", self.handle_handoff)
        app.router.add_post("/rpc/handoff_abort", self.handle_handoff_abort)
        app.router.add_post("/rpc/flip_hint", self.handle_flip_hint)
        app.router.add_get("/rpc/hello", self.handle_hello)
        app.router.add_get("/rpc/instance_info", self.handle_instance_info)
        app.router.add_get("/rpc/static_prefill_list", self.handle_prefill_list)
        app.router.add_get("/rpc/static_decode_list", self.handle_decode_list)
        app.router.add_get("/health", self.handle_hello)
        # Fleet fan-out targets reach peer frontends by their RPC address
        # (the only address the XLLM:SERVICE:* records carry), so the
        # LOCAL-scope observability surface is served here too. scope=
        # fleet is deliberately not honored on this app — a peer's fan-out
        # must terminate at one hop, never cascade.
        app.router.add_get("/metrics", self.handle_metrics)
        app.router.add_get("/admin/trace", tracing.handle_admin_trace)
        app.router.add_get("/admin/trace/recent",
                           tracing.handle_admin_trace_recent)
        app.router.add_get("/admin/profile", handle_admin_profile)
        return app

    async def _on_startup(self, app: web.Application) -> None:
        self._loop = asyncio.get_running_loop()
        self._client = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=30))

    async def _on_cleanup(self, app: web.Application) -> None:
        if self._client is not None:
            await self._client.close()
        self.tracer.close()
        PROFILER.stop()
        RECORDER.remove_context_provider("service", self._anomaly_context)
        RECORDER.remove_context_provider("native", _native.status)
        RECORDER.close()

    def _anomaly_context(self) -> dict[str, Any]:
        """Flight-recorder context provider: this frontend's control-plane
        state at anomaly time (lock-free reads only)."""
        mgr = self.scheduler.instance_mgr
        return {
            "self_addr": self.scheduler.self_addr,
            "is_master": self.scheduler.is_master,
            "snapshot_age_s": mgr.snapshot_age_s(),
            "loadinfo_ages_s": mgr.load_info_ages_s(),
            "stale_load": sorted(mgr.stale_load_names()),
            "frame_log_seq": self.scheduler.kvcache_mgr.frame_log_seq(),
            "ownership": self.scheduler.ownership.stats(),
            "inflight_requests": self.scheduler.num_inflight_requests(),
        }

    def _mirror_span(self, span: dict[str, Any]) -> None:
        self.tracer.log(span.get("request_id", ""),
                        {"type": "span", "span": span})

    @web.middleware
    async def _readiness_middleware(self, request: web.Request, handler):
        # Readiness gate (reference stops the whole HTTP server while no
        # instance group is viable, `master.cpp:101-135`; we keep the socket
        # and reject API traffic with 503 — same client-observable contract).
        if request.path.startswith("/v1/") and \
                not self.scheduler.has_available_instances():
            return _error_response(503, "no available instances",
                                   "service_unavailable")
        return await handler(request)

    # ----------------------------------------------------------- completions
    async def handle_completions(self, request: web.Request) -> web.StreamResponse:
        return await self._handle_generate(request, kind="completion")

    async def handle_chat(self, request: web.Request) -> web.StreamResponse:
        return await self._handle_generate(request, kind="chat")

    async def handle_messages(self, http_req: web.Request,
                              sid: Optional[str] = None,
                              deadline_override: int = 0
                              ) -> web.StreamResponse:
        """Anthropic-style Messages API (`/v1/messages`): the reference
        family acknowledges this surface only as an engine proto
        (`anthropic.proto` in `proto/CMakeLists.txt:18-37`) with no
        service route; here it is a first-class endpoint mapped onto the
        chat pipeline with Anthropic request/response/stream framing."""
        if sid is None:
            # Relayed handoffs already counted at their accepting
            # frontend; HANDOFF_SERVED_TOTAL tracks the owner-side serve.
            SERVER_REQUEST_IN_TOTAL.labels(kind="anthropic").inc()
        raw = await http_req.read()
        try:
            body = json.loads(raw)
        except (json.JSONDecodeError, UnicodeDecodeError):
            return _error_response(400, "invalid JSON body")
        if not isinstance(body, dict):
            return _error_response(400, "request body must be a JSON object")
        # Overload plane (same order as _handle_generate): deadline +
        # priority first, admission after the relay decision.
        priority = parse_priority(body, http_req.headers)
        deadline_ms = deadline_override or parse_deadline_ms(
            body, http_req.headers, self.opts.default_request_deadline_ms)
        if deadline_expired(deadline_ms):
            REQUESTS_CANCELLED_TOTAL.labels(reason="deadline").inc()
            return _error_response(504, "request deadline already expired",
                                   "timeout")
        handoff = sid is not None
        if not handoff:
            sid, owner, owner_key = self._assign_ownership("messages", body)
            if owner != self.scheduler.self_addr:
                RETRY_BUDGET.note_request()
                return await self._relay_to_owner(
                    http_req, raw, "messages", sid, owner, owner_key,
                    bool(body.get("stream", False)),
                    deadline_ms=deadline_ms, priority=priority)
        if not isinstance(body.get("max_tokens"), int) \
                or body["max_tokens"] < 1:
            return _error_response(400, "max_tokens is required")
        msgs = body.get("messages")
        if not isinstance(msgs, list) or not msgs:
            return _error_response(400, "messages must be a non-empty list")
        shed = self._admission_check(priority)
        if shed is not None:
            return shed
        RETRY_BUDGET.note_request()
        # Same slot-ownership discipline as _handle_generate: the finally
        # releases on every path that never registers the request.
        slot = {"held": True}
        try:
            return await self._admitted_messages(
                http_req, sid, body, msgs, priority, deadline_ms,
                handoff, slot)
        finally:
            if slot["held"]:
                ADMISSION.release()

    async def _admitted_messages(self, http_req: web.Request, sid: str,
                                 body: dict[str, Any], msgs: list,
                                 priority: str, deadline_ms: int,
                                 handoff: bool,
                                 slot: dict) -> web.StreamResponse:
        try:
            sp = _parse_sampling(body)
        except (TypeError, ValueError, AttributeError) as e:
            return _error_response(400, f"invalid request field: {e}")
        stops = body.get("stop_sequences")
        if isinstance(stops, list):
            sp.stop = [str(s) for s in stops]
        sp.max_tokens = BROWNOUT.clamp_max_tokens(priority, sp.max_tokens)
        body["max_tokens"] = sp.max_tokens
        req = Request(
            service_request_id=sid,
            request_id="msg_" + short_uuid(),
            model=body.get("model", self.opts.model_id or ""),
            stream=bool(body.get("stream", False)),
            priority_class=priority,
            deadline_ms=deadline_ms,
            sampling=sp,
        )
        # Anthropic carries the system prompt out-of-band; normalize
        # content blocks to the chat-template message shape.
        norm: list[dict[str, Any]] = []
        system = body.get("system")
        if isinstance(system, str) and system:
            norm.append({"role": "system", "content": system})
        for m in msgs:
            if not isinstance(m, dict):
                return _error_response(400, "invalid message entry")
            content = m.get("content")
            if isinstance(content, list):
                content = "".join(p.get("text", "") for p in content
                                  if isinstance(p, dict)
                                  and p.get("type") == "text")
            norm.append({"role": m.get("role", "user"),
                         "content": str(content or "")})
        req.messages = norm
        if self.tracer.enabled:
            req.trace_callback = self.tracer.log
            self.tracer.log(req.service_request_id, {"request": body})
        self._start_root_span(
            req, "anthropic",
            ctx=TraceContext.from_headers(http_req.headers) if handoff
            else None)

        t0 = time.perf_counter()
        status = await asyncio.get_running_loop().run_in_executor(
            self.scheduler.schedule_executor, self.scheduler.schedule, req)
        HOTPATH.record("schedule", (time.perf_counter() - t0) * 1000)
        if not status.ok():
            if req.span:
                req.span.end(f"ERROR: {status.code.name}")
            return _error_response(
                503 if status.code.name == "UNAVAILABLE" else 400,
                status.message, "service_unavailable"
                if status.code.name == "UNAVAILABLE" else "invalid_request_error")

        conn = AioConnection(asyncio.get_running_loop(), req.stream)
        enriched: dict[str, Any] = {
            "model": req.model,
            "service_request_id": req.service_request_id,
            "source_service_addr": self.scheduler.self_addr,
            "token_ids": req.token_ids,
            "max_tokens": body["max_tokens"],
            "temperature": body.get("temperature", 1.0),
            "stream": req.stream,
            "messages": norm,
            "stop": sp.stop,
            "routing": {"prefill_name": req.routing.prefill_name,
                        "decode_name": req.routing.decode_name,
                        "encode_name": req.routing.encode_name},
        }
        if req.deadline_ms:
            enriched["deadline_ms"] = req.deadline_ms
        if body.get("top_p") is not None:
            enriched["top_p"] = body["top_p"]
        if body.get("top_k") is not None:
            enriched["top_k"] = body["top_k"]
        if req.trace is not None:
            enriched["trace_context"] = req.trace.to_dict()
        req.admitted = True
        slot["held"] = False
        self.scheduler.record_new_request(
            req, conn, "anthropic",
            forward_path="/v1/chat/completions", forward_payload=enriched)
        task = asyncio.create_task(self._forward_to_instance(
            req, conn, "/v1/chat/completions", enriched))
        self._forward_tasks.add(task)
        task.add_done_callback(self._forward_tasks.discard)
        return await self._respond(http_req, req, conn, emit_done=False)

    def _start_root_span(self, req: Request, kind: str,
                         ctx: Optional[TraceContext] = None) -> None:
        """Root the request's trace in the frontend (no-op when tracing is
        off): every downstream hop parents its spans under this context.
        With `ctx` (a relayed handoff: the accepting frontend rooted the
        trace and sent it as x-xllm-* headers) this span parents under
        the relay instead, so /admin/trace assembles ONE tree across the
        accepting frontend, every owner incarnation, and the engines."""
        root = TRACER.start_span("frontend.request", ctx=ctx,
                                 request_id=req.service_request_id,
                                 kind=kind, model=req.model,
                                 stream=req.stream)
        if root:
            req.span = root
            req.trace = root.context()

    # ------------------------------------------------- multi-master ownership
    def _assign_ownership(self, kind: str,
                          body: dict[str, Any]) -> tuple[str, str, str]:
        """(service_request_id, owner_addr, ownership_key) for a new
        accept. A client-pinned string `ownership_key` in the body gives
        session affinity — every request carrying the same key is owned
        by the same master (and fails over to the same successor);
        otherwise the generated id is mined so that, in the common case,
        this frontend owns what it accepts and no forward hop is paid."""
        ownership = self.scheduler.ownership
        okey = body.get("ownership_key")
        if isinstance(okey, str) and okey:
            return (generate_service_request_id(kind),
                    ownership.owner_of(okey), okey)
        sid, owner = ownership.mine(kind)
        return sid, owner, sid

    async def _relay_to_owner(self, http_req: web.Request, raw: bytes,
                              kind: str, sid: str, owner: str,
                              owner_key: str, stream: bool,
                              deadline_ms: int = 0,
                              priority: str = "") -> web.StreamResponse:
        assert self._client is not None
        return await self._relay.relay(
            http_req, self._client, raw, kind, sid, owner, owner_key,
            stream, self.opts.request_timeout_s,
            deadline_ms=deadline_ms, priority=priority)

    def _admission_check(self, priority: str) -> Optional[web.Response]:
        """Overload-admission gate (overload/admission.py): None =
        admitted (the caller must set `req.admitted` so exit accounting
        releases the slot), else the fast 429. Runs on the event loop —
        one leaf-lock hold over integer math, no RPC, no tokenize."""
        admit, reason, retry_after = ADMISSION.try_admit(
            priority,
            live=len(self.scheduler.instance_mgr
                     .routing_snapshot().schedulable),
            burn_hot=BROWNOUT.active())
        if admit:
            return None
        REQUESTS_CANCELLED_TOTAL.labels(reason="shed").inc()
        return web.json_response(
            {"error": {"message": f"overloaded: {reason}",
                       "type": "overloaded_error", "code": 429}},
            status=429,
            headers={"Retry-After": str(max(1, int(retry_after)))})

    async def _handle_generate(self, http_req: web.Request, kind: str,
                               sid: Optional[str] = None,
                               deadline_override: int = 0
                               ) -> web.StreamResponse:
        if sid is None:
            # Relayed handoffs already counted at their accepting
            # frontend; HANDOFF_SERVED_TOTAL tracks the owner-side serve.
            SERVER_REQUEST_IN_TOTAL.labels(kind=kind).inc()
        raw = await http_req.read()
        try:
            body = json.loads(raw)
        except (json.JSONDecodeError, UnicodeDecodeError):
            return _error_response(400, "invalid JSON body")
        if not isinstance(body, dict):
            return _error_response(400, "request body must be a JSON object")

        # Overload plane: resolve the end-to-end deadline and the
        # priority class BEFORE any expensive work. A relayed handoff
        # carries the ABSOLUTE deadline the accepting frontend computed
        # (re-parsing the body's relative budget here would extend the
        # deadline by the relay hop it is meant to bound).
        priority = parse_priority(body, http_req.headers)
        deadline_ms = deadline_override or parse_deadline_ms(
            body, http_req.headers, self.opts.default_request_deadline_ms)
        if deadline_expired(deadline_ms):
            # Admission rejects already-expired work: serving it burns
            # fleet capacity on an answer nobody is waiting for.
            REQUESTS_CANCELLED_TOTAL.labels(reason="deadline").inc()
            return _error_response(504, "request deadline already expired",
                                   "timeout")

        # Multi-master ownership: `sid` set means this request was relayed
        # here by its accepting frontend — serve it locally under the
        # relay-supplied id (never re-relay). Otherwise resolve ownership
        # and forward the raw body to the owner when it isn't us.
        handoff = sid is not None
        if not handoff:
            sid, owner, owner_key = self._assign_ownership(kind, body)
            if owner != self.scheduler.self_addr:
                # Relay-path deposit: the relay's re-ownership recovery
                # spends from THIS process's retry bucket.
                RETRY_BUDGET.note_request()
                return await self._relay_to_owner(
                    http_req, raw, kind, sid, owner, owner_key,
                    bool(body.get("stream", False)),
                    deadline_ms=deadline_ms, priority=priority)

        # Admission control + priority shedding: the bounded gate in
        # front of the schedule executor — a fast 429 beats a slow 200
        # that blows everyone's SLO. Runs at the serving frontend (the
        # owner, for relayed requests): the watermark protects THIS
        # process's executor and the fleet behind it.
        shed = self._admission_check(priority)
        if shed is not None:
            return shed
        RETRY_BUDGET.note_request()
        # Slot ownership: held HERE from try_admit until the request is
        # registered (record_new_request — from then on the scheduler's
        # winning-exit accounting releases it via `req.admitted`). The
        # finally releases on EVERY other path — validation errors,
        # schedule failure, a raising parser, handler-task cancellation
        # — or a shed slot would leak forever.
        slot = {"held": True}
        try:
            return await self._admitted_generate(
                http_req, kind, sid, body, priority, deadline_ms,
                handoff, slot)
        finally:
            if slot["held"]:
                ADMISSION.release()

    async def _admitted_generate(self, http_req: web.Request, kind: str,
                                 sid: str, body: dict[str, Any],
                                 priority: str, deadline_ms: int,
                                 handoff: bool,
                                 slot: dict) -> web.StreamResponse:
        try:
            req = Request(
                service_request_id=sid,
                request_id=("chatcmpl-" if kind == "chat" else "cmpl-") + short_uuid(),
                model=body.get("model", self.opts.model_id or ""),
                stream=bool(body.get("stream", False)),
                include_usage=bool((body.get("stream_options") or {})
                                   .get("include_usage", False)),
                offline=bool(body.get("offline", False)),
                priority=int(body.get("priority") or 0),
                priority_class=priority,
                deadline_ms=deadline_ms,
                sampling=_parse_sampling(body),
            )
        except (TypeError, ValueError, AttributeError) as e:
            # Mistyped client fields (e.g. "max_tokens": null) are client
            # errors, not 500s.
            return _error_response(400, f"invalid request field: {e}")
        # Brownout: clamp batch-priority generation length while the SLO
        # burn is hot — bulk work finishes sooner and returns decode
        # capacity to interactive traffic. The body is clamped too: the
        # enriched engine payload is built from it.
        clamped = BROWNOUT.clamp_max_tokens(priority,
                                            req.sampling.max_tokens)
        if clamped != req.sampling.max_tokens:
            req.sampling.max_tokens = clamped
            body["max_tokens"] = clamped
            body.pop("max_completion_tokens", None)
        if kind == "chat":
            msgs = body.get("messages")
            if not isinstance(msgs, list) or not msgs:
                return _error_response(400, "messages must be a non-empty list")
            req.messages = msgs
            req.tools = body.get("tools") or []
            req.chat_template_kwargs = body.get("chat_template_kwargs") or {}
            req.has_images = any(
                isinstance(m.get("content"), list) and any(
                    isinstance(part, dict)
                    and str(part.get("type", "")).startswith("image")
                    for part in m["content"])
                for m in msgs if isinstance(m, dict))
        else:
            prompt = body.get("prompt", "")
            if isinstance(prompt, list):
                if prompt and isinstance(prompt[0], int):
                    req.token_ids = [int(t) for t in prompt]
                else:
                    prompt = "".join(str(p) for p in prompt)
            if isinstance(prompt, str):
                req.prompt = prompt
            if not req.prompt and not req.token_ids:
                return _error_response(400, "prompt must not be empty")
        if self.tracer.enabled:
            req.trace_callback = self.tracer.log
            self.tracer.log(req.service_request_id, {"request": body})
        self._start_root_span(
            req, kind,
            ctx=TraceContext.from_headers(http_req.headers) if handoff
            else None)

        # Schedule (tokenize + route) off the event loop — CPU-bound, on
        # the dedicated bounded pool so admission never queues behind
        # generations ingest or failover backoff sleeps.
        t0 = time.perf_counter()
        status = await asyncio.get_running_loop().run_in_executor(
            self.scheduler.schedule_executor, self.scheduler.schedule, req)
        HOTPATH.record("schedule", (time.perf_counter() - t0) * 1000)
        if not status.ok():
            if req.span:
                req.span.end(f"ERROR: {status.code.name}")
            # A failed schedule is never registered, so exit accounting
            # will not release its admission slot — the caller's finally
            # does.
            return _error_response(
                503 if status.code.name == "UNAVAILABLE" else 400,
                status.message, "service_unavailable"
                if status.code.name == "UNAVAILABLE" else "invalid_request_error")

        conn = AioConnection(asyncio.get_running_loop(), req.stream)

        # Enrich + forward to the prefill instance, fire-and-forget
        # (reference `service.cpp:222-260,485-493`). The enriched payload
        # is also retained with the request registration so the failover
        # layer can replay it on a surviving instance; the wire bytes are
        # preserialized HERE, once, in the instance's negotiated format
        # (msgpack for current engines — token_ids is a multi-thousand-int
        # list; JSON-encoding it per request was a measured hot-path cost).
        t1 = time.perf_counter()
        enriched = dict(body)
        enriched["service_request_id"] = req.service_request_id
        enriched["source_service_addr"] = self.scheduler.self_addr
        enriched["token_ids"] = req.token_ids
        enriched["routing"] = {"prefill_name": req.routing.prefill_name,
                               "decode_name": req.routing.decode_name,
                               "encode_name": req.routing.encode_name}
        if req.deadline_ms:
            # Absolute deadline on the engine wire: the engine compares
            # against its own clock, so queueing/transit time is
            # naturally subtracted from the remaining budget.
            enriched["deadline_ms"] = req.deadline_ms
        if req.trace is not None:
            enriched["trace_context"] = req.trace.to_dict()
        path = "/v1/chat/completions" if kind == "chat" else "/v1/completions"
        wire_body, wire_ctype = wire.encode_dispatch(
            enriched, self.scheduler.dispatch_wire(req.routing.prefill_name))
        HOTPATH.record("enrich", (time.perf_counter() - t1) * 1000)
        # Admission-slot ownership transfers to the scheduler with the
        # registration: its exactly-once exit accounting releases.
        req.admitted = True
        slot["held"] = False
        self.scheduler.record_new_request(req, conn, kind,
                                          forward_path=path,
                                          forward_payload=enriched)
        task = asyncio.create_task(
            self._forward_to_instance(req, conn, path, enriched,
                                      wire_body, wire_ctype))
        self._forward_tasks.add(task)
        task.add_done_callback(self._forward_tasks.discard)

        # Owner-side delta journal for relayed streams: every emitted SSE
        # data frame is teed into it so a relay reconnect (transport blip,
        # accepting-frontend restart) replays the exact frames instead of
        # re-running the generation.
        journal = self._journal.start(sid) \
            if handoff and req.stream else None
        return await self._respond(http_req, req, conn, journal=journal)

    async def _forward_to_instance(self, req: Request, conn: AioConnection,
                                   path: str, payload: dict[str, Any],
                                   body: Optional[bytes] = None,
                                   ctype: str = wire.JSON_CONTENT_TYPE) -> None:
        url = f"http://{req.routing.prefill_name}{path}"
        if body is None:
            body, ctype = wire.encode_dispatch(payload)
        retryable, code = True, 503
        try:
            assert self._client is not None
            t0 = time.perf_counter()
            async with self._client.post(
                    url, data=body,
                    headers={"Content-Type": ctype}) as resp:
                if resp.status == 415 \
                        and ctype == wire.MSGPACK_CONTENT_TYPE:
                    # Legacy engine behind a stale registration: negotiate
                    # down to JSON for this instance and re-send. A 415
                    # rejection cannot have started generation, so the
                    # re-send is safe on this non-idempotent wire.
                    self.scheduler.instance_mgr.demote_wire(
                        req.routing.prefill_name)
                    body, ctype = wire.encode_dispatch(payload)
                    async with self._client.post(
                            url, data=body,
                            headers={"Content-Type": ctype}) as retry:
                        if retry.status != 200:
                            text = await retry.text()
                            if 400 <= retry.status < 500:
                                retryable, code = False, retry.status
                            raise RuntimeError(
                                f"engine returned {retry.status}: "
                                f"{text[:200]}")
                elif resp.status != 200:
                    text = await resp.text()
                    # 4xx = the engine deliberately rejected the request
                    # (client error): another instance would reject it the
                    # same way — surface it as-is, don't failover.
                    if 400 <= resp.status < 500:
                        retryable, code = False, resp.status
                    raise RuntimeError(f"engine returned {resp.status}: {text[:200]}")
            HOTPATH.record("forward", (time.perf_counter() - t0) * 1000)
            self.scheduler.mark_dispatch_complete(req)
        except Exception as e:  # noqa: BLE001 — surface any forward failure
            logger.warning("forward of %s to %s failed: %s",
                           req.service_request_id, url, e)
            # Failover-or-surface (the reference handle_first_send_request
            # path only surfaces). Off the event loop: the failover layer
            # sleeps on backoff and issues blocking engine RPCs.
            await asyncio.get_running_loop().run_in_executor(
                None, self.scheduler.handle_dispatch_failure, req,
                f"failed to reach prefill instance: {e}", retryable, code)

    async def _respond(self, http_req: web.Request, req: Request,
                       conn: AioConnection,
                       emit_done: bool = True,
                       journal=None) -> web.StreamResponse:
        timeout = self.opts.request_timeout_s
        if req.deadline_ms:
            # The client-side wait honors the per-request deadline (plus
            # a small grace for in-flight deltas) so a stalled stream
            # surfaces its 504 at deadline, not at the blunt GC bound.
            timeout = max(0.05, min(
                timeout, remaining_ms(req.deadline_ms) / 1000.0 + 0.25))
        if req.stream:
            resp = web.StreamResponse()
            resp.headers["Content-Type"] = "text/event-stream"
            resp.headers["Cache-Control"] = "no-cache"
            resp.headers["Connection"] = "keep-alive"
            # The internal service id (the key /admin/trace and the
            # flight recorder index by) — deltas only carry the
            # OpenAI-style cmpl- id, so without this header a client
            # cannot correlate its own request with the trace plane.
            resp.headers["X-Request-Id"] = req.service_request_id
            await resp.prepare(http_req)
            # Coalesced emit: one blocking queue get, then drain whatever
            # else is already queued and flush ALL frames in one write()
            # — an engine delta batch (several tokens per Generations
            # POST) costs one event-loop write instead of one per chunk.
            # With a `journal` (owner side of a relayed stream) every
            # data frame is teed into it, and a broken downstream
            # connection DETACHES instead of cancelling: deltas keep
            # absorbing into the journal for the reconnect grace window
            # so a relay retry replays the exact stream.
            dumps = json.dumps  # xlint: allow-hot-json(SSE frames are client-protocol JSON, not the negotiated dispatch wire)
            buf = bytearray()
            done = False
            detached = False
            detach_deadline = 0.0
            try:
                while not done:
                    get_timeout = timeout
                    if detached:
                        # A reconnect (journal get) or an actively-
                        # streaming replay (per-poll touch) extends the
                        # grace: cancelling a generation whose frames a
                        # reattached relay is mid-replay would truncate
                        # the stream (review catch).
                        extended = max(
                            detach_deadline,
                            journal.touched + self._journal.grace_s)
                        remaining = extended - time.monotonic()
                        if remaining <= 0:
                            # Nobody (re)attached inside the grace
                            # window: normal disconnect semantics from
                            # here. Finish the journal so a late replay
                            # drains what exists and exits instead of
                            # polling to the request-timeout bound.
                            DeltaJournal.finish(journal)
                            conn.mark_disconnected()
                            break
                        get_timeout = min(timeout, remaining)
                    try:
                        tag, item = await asyncio.wait_for(conn.queue.get(),
                                                           get_timeout)
                    except asyncio.TimeoutError:
                        if detached:
                            continue   # re-check the grace window
                        raise
                    # The drain below is pure CPU (no awaits): frame
                    # assembly + JSON serialization per delta — the
                    # profiler's hottest output-lane work. Attributed to
                    # the "stream" loop so the native-on/off A/B is
                    # measured where the bytes are built; libhotcore
                    # assembles data/event frames in one C call when it
                    # can (error frames are rare and ensure_ascii, so
                    # they stay on the Python encoder).
                    with CPU_ATTR.measure("stream"):
                        while True:
                            frame = b""
                            if AioConnection.is_finish(tag):
                                if emit_done:  # OpenAI framing
                                    frame = _DONE_FRAME
                                done = True
                            elif tag == "error":
                                code, msg = item
                                frame = _DATA_PREFIX + dumps(
                                    {"error": {"message": msg,
                                               "code": code}},
                                    separators=_COMPACT).encode() \
                                    + _FRAME_SEP
                                done = True
                            elif tag == "event":
                                name, obj = item
                                frame = _native.sse_event_frame(name, obj)
                                if frame is _native.MISS:
                                    frame = (f"event: {name}\n".encode()
                                             + _DATA_PREFIX
                                             + dumps(obj, ensure_ascii=False,
                                                     separators=_COMPACT
                                                     ).encode()
                                             + _FRAME_SEP)
                            else:
                                frame = _native.sse_data_frame(item)
                                if frame is _native.MISS:
                                    frame = _DATA_PREFIX + dumps(
                                        item, ensure_ascii=False,
                                        separators=_COMPACT).encode() \
                                        + _FRAME_SEP
                            if frame:
                                buf += frame
                                if journal is not None:
                                    DeltaJournal.record(journal, frame)
                            if done:
                                break
                            try:
                                tag, item = conn.queue.get_nowait()
                            except asyncio.QueueEmpty:
                                break
                    if buf:
                        if not detached:
                            try:
                                await resp.write(bytes(buf))
                            except (ConnectionResetError, OSError):
                                if journal is None:
                                    raise
                                detached = True
                                detach_deadline = time.monotonic() + \
                                    self._journal.grace_s
                                logger.info(
                                    "relay connection of %s broke after "
                                    "%d journaled frames; absorbing "
                                    "deltas for reconnect (%.1fs grace)",
                                    req.service_request_id,
                                    len(journal.frames),
                                    self._journal.grace_s)
                        buf.clear()
                if done and journal is not None:
                    DeltaJournal.finish(journal)
            except asyncio.TimeoutError:
                if await self._deadline_cancel(req):
                    # Surface the 504 in-band: frames may already be out.
                    with contextlib.suppress(ConnectionResetError, OSError):
                        await resp.write(
                            b'data: {"error": {"message": "deadline '
                            b'exceeded", "code": 504}}\n\n')
                else:
                    conn.mark_disconnected()
            except (ConnectionResetError, OSError):
                conn.mark_disconnected()
            except asyncio.CancelledError:
                conn.mark_disconnected()
                raise
            if not detached:
                with contextlib.suppress(ConnectionResetError):
                    await resp.write_eof()
            return resp
        # Non-stream.
        try:
            while True:
                tag, item = await asyncio.wait_for(conn.queue.get(), timeout)
                if AioConnection.is_finish(tag):
                    continue  # finish after single payload: loop exits below
                if tag == "error":
                    code, msg = item
                    return _error_response(code, msg, "server_error")
                return web.json_response(
                    item, headers={"X-Request-Id": req.service_request_id})
        except asyncio.TimeoutError:
            if await self._deadline_cancel(req):
                return _error_response(504, "deadline exceeded", "timeout")
            conn.mark_disconnected()
            return _error_response(504, "request timed out", "timeout")
        except asyncio.CancelledError:
            conn.mark_disconnected()
            raise

    async def _deadline_cancel(self, req: Request) -> bool:
        """A response wait timed out: if the request's own deadline has
        expired, cancel it for real (exit accounting + engine-side stop
        — blocking RPCs, so off the event loop). False = not a deadline
        case; the caller falls back to disconnect semantics."""
        if not deadline_expired(req.deadline_ms):
            return False
        await asyncio.get_running_loop().run_in_executor(
            None, self.scheduler.cancel_request, req.service_request_id,
            504, "deadline exceeded", "deadline")
        return True

    # -------------------------------------------------------- other routes
    async def handle_models(self, request: web.Request) -> web.Response:
        """Aggregate model list from instance metadata (reference proxies an
        instance's Models RPC, `service.cpp:317-357`)."""
        models: dict[str, dict[str, Any]] = {}
        for meta in self.scheduler.instance_mgr.list_instances():
            for m in meta.models or ([self.opts.model_id] if self.opts.model_id else []):
                if m:
                    models.setdefault(m, {
                        "id": m, "object": "model", "created": 0,
                        "owned_by": "xllm-service-tpu"})
        return web.json_response({"object": "list",
                                  "data": list(models.values())})

    async def handle_embeddings(self, request: web.Request) -> web.Response:
        """Synchronous proxy to an engine's embedding forward. (The
        reference returns "not support" here, `service.cpp:500-517` — we
        exceed it; engines whose model family lacks an embed forward still
        501.)"""
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return _error_response(400, "invalid JSON")
        routing = self.scheduler.instance_mgr.get_next_instance_pair()
        if not routing.valid():
            return _error_response(503, "no available instances",
                                   "service_unavailable")
        ch = self.scheduler.get_channel(routing.prefill_name)
        if ch is None:
            return _error_response(503, "instance channel unavailable",
                                   "service_unavailable")
        forward = getattr(ch, "forward_status", None)
        if forward is None:   # test doubles without the richer API
            ok, resp = await asyncio.get_running_loop().run_in_executor(
                None, ch.forward, "/v1/embeddings", body)
            if not ok:
                return _error_response(502, f"engine error: {resp}")
            return web.json_response(resp)
        status, resp = await asyncio.get_running_loop().run_in_executor(
            None, forward, "/v1/embeddings", body)
        if status != 200:
            # Pass the engine's own status through (501 unsupported
            # family, 400 bad input, ...) instead of masking as 502.
            msg = resp.get("error") if isinstance(resp, dict) else resp
            return _error_response(status if 400 <= status < 600 else 502,
                                   str(msg))
        return web.json_response(resp)

    def _refresh_local_gauges(self) -> None:
        """Scrape-time refresh of the control-plane freshness gauges +
        the SLO burn rates (cheap lock-free reads; no background
        thread)."""
        mgr = self.scheduler.instance_mgr
        ROUTING_SNAPSHOT_AGE_SECONDS.set(mgr.snapshot_age_s())
        ages = mgr.load_info_ages_s()
        # A never-updated instance (age sentinel -1) IS the stalest case
        # — routing has zero telemetry for it; it must win the gauge,
        # not be hidden by a fresher peer's finite age.
        LOADINFO_MAX_AGE_SECONDS.set(
            -1.0 if any(a < 0 for a in ages.values())
            else max(ages.values(), default=0.0))
        for name, age in ages.items():
            # Per-instance snapshot age (ISSUE 15 satellite): the exact
            # staleness SLO/CAR scoring discounts by. Series ride the
            # live load-info view; deregistration evicts them.
            LOADINFO_AGE_SECONDS.labels(instance=name).set(age)
        LOADINFO_STALE_INSTANCES.set(len(mgr.stale_load_names()))
        KVCACHE_FRAME_LOG_SEQ.set(
            self.scheduler.kvcache_mgr.frame_log_seq())
        # Autoscaler surface: fleet census by role + decision freshness
        # (a stuck control loop shows up as a growing age).
        snap = mgr.routing_snapshot()
        FLEET_SIZE.labels(role="prefill").set(len(snap.prefill))
        FLEET_SIZE.labels(role="decode").set(len(snap.decode))
        FLEET_SIZE.labels(role="encode").set(len(snap.encode))
        FLEET_SIZE.labels(role="draining").set(len(mgr.draining_names()))
        AUTOSCALER_LAST_DECISION_AGE_SECONDS.set(
            self.scheduler.autoscaler.last_decision_age_s())
        # Overload plane: gate depth, brownout state, retry-budget level.
        ADMISSION_PENDING_REQUESTS.set(ADMISSION.pending())
        BROWNOUT_ACTIVE.set(1.0 if BROWNOUT.active() else 0.0)
        tokens = RETRY_BUDGET.tokens()
        RETRY_BUDGET_TOKENS.set(tokens if tokens != float("inf") else -1.0)
        SLO_MONITOR.export_gauges()
        # Hot-loop CPU attribution as counters: the per-master scaling
        # series /metrics/fleet captures (ISSUE 18 satellite).
        CPU_ATTR.export_counters()
        # Which libhotcore components serve this process (1) vs run the
        # pure-Python fallback (0) — fleet scrapes spot degraded peers.
        _native.export_gauges()

    async def handle_metrics(self, request: web.Request) -> web.Response:
        self._refresh_local_gauges()
        return web.Response(text=REGISTRY.render_prometheus(),
                            content_type="text/plain")

    # ----------------------------------------------- fleet observability
    def _fleet_targets(self) -> list[tuple[str, str]]:
        """(addr, role) fan-out targets: every known engine agent (from
        the RCU routing snapshot — SUSPECT/draining included, their view
        may hold the evidence) and every peer frontend (ownership member
        set)."""
        targets = [(name, "engine") for name in
                   self.scheduler.instance_mgr.routing_snapshot().entries]
        self_addr = self.scheduler.self_addr
        targets += [(addr, "frontend")
                    for addr in self.scheduler.ownership.members()
                    if addr != self_addr]
        return targets

    async def _fanout_get(self, path: str, params: dict[str, str],
                          as_json: bool = True
                          ) -> list[tuple[str, str, str, Any]]:
        """Concurrent bounded GET against every fleet target. Returns
        ``(addr, role, status, payload)`` rows where status is ``ok``,
        ``http <code>``, ``timeout`` or ``error`` — a dead peer degrades
        the view, never the endpoint."""
        assert self._client is not None
        timeout = aiohttp.ClientTimeout(
            total=max(0.1, self.opts.fleet_peer_timeout_s))

        async def one(addr: str, role: str):
            async with self._fleet_sem:
                try:
                    async with self._client.get(
                            f"http://{addr}{path}", params=params,
                            timeout=timeout) as r:
                        payload = (await r.json(content_type=None)
                                   if as_json else await r.text())
                        status = "ok" if r.status == 200 \
                            else f"http {r.status}"
                        return addr, role, status, payload
                except asyncio.TimeoutError:
                    return addr, role, "timeout", None
                except (aiohttp.ClientError, OSError, ValueError) as e:
                    return addr, role, f"error: {type(e).__name__}", None

        return list(await asyncio.gather(
            *(one(a, r) for a, r in self._fleet_targets())))

    async def handle_admin_trace(self, request: web.Request) -> web.Response:
        if request.query.get("scope") != "fleet":
            return await tracing.handle_admin_trace(request)
        request_id = request.query.get("request_id", "")
        trace_id = request.query.get("trace_id", "")
        if not request_id and not trace_id:
            return _error_response(400, "pass request_id= or trace_id=")
        status, local = TRACER.query_trace(request_id=request_id,
                                           trace_id=trace_id)
        span_lists: list[list[dict[str, Any]]] = []
        if status == 200:
            span_lists.append(local["spans"])
            trace_id = trace_id or local["trace_id"]
        # Peers resolve request_id against their own stores, so the
        # fan-out works even when this frontend recorded nothing (e.g. a
        # trace rooted by a peer that relayed elsewhere).
        params = {"trace_id": trace_id} if trace_id \
            else {"request_id": request_id}
        peers: dict[str, dict[str, str]] = {}
        for addr, role, pstatus, payload in await self._fanout_get(
                "/admin/trace", params):
            if pstatus == "ok" and isinstance(payload, dict):
                span_lists.append(payload.get("spans") or [])
                trace_id = trace_id or payload.get("trace_id", "")
            elif pstatus == "http 404":
                pstatus = "no_spans"   # a peer this trace never touched
            peers[addr] = {"role": role, "status": pstatus}
        spans = merge_fleet_spans(span_lists)
        if not spans:
            return web.json_response(
                {"error": "no spans recorded anywhere in the fleet",
                 "scope": "fleet", "peers": peers}, status=404)
        payload = {
            "scope": "fleet",
            "trace_id": trace_id,
            "request_id": request_id or next(
                (s["request_id"] for s in spans if s.get("request_id")), ""),
            "num_spans": len(spans),
            "peers": peers,
            "spans": spans,
            "tree": span_tree(spans),
        }
        # TTFT critical path over the MERGED tree: on a relayed request
        # the root span lives on the accepting frontend and the prefill
        # span on an engine — only the fleet view can decompose it.
        cp = critical_path(spans)
        if cp is not None:
            payload["critical_path"] = cp
        return web.json_response(payload)

    async def handle_admin_trace_recent(self,
                                        request: web.Request) -> web.Response:
        if request.query.get("scope") != "fleet":
            return await tracing.handle_admin_trace_recent(request)
        try:
            limit = int(request.query.get("limit", 20))
        except ValueError:
            return _error_response(400, "limit must be an integer")
        sort = request.query.get("sort", "recent")
        local = TRACER.query_recent(limit=limit, sort=sort)
        rows: dict[str, dict[str, Any]] = {
            r["trace_id"]: r for r in local["traces"]}
        peers: dict[str, dict[str, str]] = {}
        for addr, role, pstatus, payload in await self._fanout_get(
                "/admin/trace/recent",
                {"limit": str(limit), "sort": sort}):
            if pstatus == "ok" and isinstance(payload, dict):
                for r in payload.get("traces") or ():
                    cur = rows.get(r.get("trace_id", ""))
                    # Keep the row closest to the root (a frontend's view
                    # names the root point; an engine's view doesn't).
                    if cur is None or (not cur.get("root_point")
                                       and r.get("root_point")):
                        rows[r["trace_id"]] = r
            peers[addr] = {"role": role, "status": pstatus}
        key = "duration_ms" if sort == "slowest" else "start_ms"
        merged = sorted(rows.values(), key=lambda r: r.get(key, 0.0),
                        reverse=True)[:max(0, limit)]
        return web.json_response({"scope": "fleet", "sort": sort,
                                  "peers": peers, "traces": merged})

    async def handle_admin_profile(self,
                                   request: web.Request) -> web.Response:
        """Continuous-profiling view (profiling/sampler.py). Default
        scope serves this process's folded stacks / top-N summary;
        ``?scope=fleet`` fans out to every live engine agent and peer
        frontend, merges the folded counts exactly (role prefixes keep
        per-role attribution across processes) and marks each peer's
        contribution — a dead peer degrades the view, never the
        endpoint."""
        if request.query.get("scope") != "fleet":
            return await handle_admin_profile(request)
        try:
            top = int(request.query.get("top", 30))
        except ValueError:
            return _error_response(400, "top must be an integer")
        counts = parse_folded(PROFILER.folded())
        peers: dict[str, dict[str, str]] = {}
        for addr, role, pstatus, payload in await self._fanout_get(
                "/admin/profile", {"format": "folded"}, as_json=False):
            if pstatus == "ok" and isinstance(payload, str):
                for stack, n in parse_folded(payload).items():
                    counts[stack] = counts.get(stack, 0) + n
            peers[addr] = {"role": role, "status": pstatus}
        if request.query.get("format") == "folded":
            lines = [f"{';'.join(stack)} {n}"
                     for stack, n in sorted(counts.items())]
            return web.Response(text="\n".join(lines) + "\n",
                                content_type="text/plain")
        merged = summarize_stacks(counts, top_n=top)
        merged.update({"scope": "fleet", "peers": peers})
        return web.json_response(merged)

    async def handle_metrics_fleet(self,
                                   request: web.Request) -> web.Response:
        """Merged fleet Prometheus exposition: local series + every peer
        frontend's + every engine agent's /metrics, each sample re-labeled
        with ``instance``/``role``, behind a short TTL cache. A dead
        target contributes only ``fleet_scrape_up 0`` — partial, never an
        error."""
        now = time.monotonic()
        cached = self._fleet_metrics_cache
        if cached is not None and now < cached[0]:
            return web.Response(text=cached[1], content_type="text/plain")
        self._refresh_local_gauges()
        self_addr = self.scheduler.self_addr
        parts = [relabel_prometheus_text(REGISTRY.render_prometheus(),
                                         self_addr, "frontend")]
        up_lines = ["# TYPE fleet_scrape_up gauge",
                    f'fleet_scrape_up{{instance="{self_addr}",'
                    f'role="frontend"}} 1']
        for addr, role, pstatus, payload in await self._fanout_get(
                "/metrics", {}, as_json=False):
            up = 1 if pstatus == "ok" and isinstance(payload, str) else 0
            up_lines.append(f'fleet_scrape_up{{instance="{addr}",'
                            f'role="{role}"}} {up}')
            if up:
                # Foreign comments dropped: duplicate # TYPE lines across
                # sources would make the merged exposition invalid.
                parts.append(relabel_prometheus_text(
                    payload, addr, role, strip_comments=True))
        text = "".join(parts) + "\n".join(up_lines) + "\n"
        self._fleet_metrics_cache = (
            now + max(0.0, self.opts.metrics_fleet_cache_ttl_s), text)
        return web.Response(text=text, content_type="text/plain")

    async def handle_slo(self, request: web.Request) -> web.Response:
        """Scored SLO report: per-objective multi-window burn rates
        (common/slo.py) — the machine-readable signal the autoscaling /
        SLO-policy loop consumes."""
        report = SLO_MONITOR.export_gauges()
        report["targets"] = {
            "slo_ttft_ms": self.opts.slo_ttft_ms,
            "slo_tpot_ms": self.opts.slo_tpot_ms,
            "slo_error_budget": self.opts.slo_error_budget,
        }
        return web.json_response(report)

    async def handle_hello(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "ok",
                                  "master": self.scheduler.is_master})

    # Live-reloadable knobs (reference exposes target_ttft/target_tpot as
    # brpc-reloadable flags with validation, `global_gflags.cpp:122-132`).
    _RELOADABLE = {"target_ttft_ms": float, "target_tpot_ms": float,
                   "max_waiting_requests": int, "request_timeout_s": float,
                   "enable_request_trace": _cast_bool,
                   "enable_tracing": _cast_bool,
                   "trace_sample_rate": float}

    async def handle_get_config(self, request: web.Request) -> web.Response:
        import dataclasses

        return web.json_response({
            f.name: getattr(self.opts, f.name)
            for f in dataclasses.fields(self.opts)
            if isinstance(getattr(self.opts, f.name), (int, float, str, bool))
        })

    async def handle_planner(self, request: web.Request) -> web.Response:
        """Latest fleet-planning decision (scale hints + requested flips;
        reference Planner component, docs/en/overview.md:56-60)."""
        import dataclasses

        d = self.scheduler.planner.last_decision
        if d is None:
            return web.json_response({"decision": None})
        return web.json_response({"decision": dataclasses.asdict(d)})

    async def handle_autoscaler(self, request: web.Request) -> web.Response:
        """The autoscaler controller's decision log + state
        (docs/autoscaling.md): every tick's inputs, actions and the
        reasons they were (or were not) taken — PlanDecision.reasons,
        but acted on."""
        return web.json_response(self.scheduler.autoscaler.report())

    async def handle_coordination(self, request: web.Request) -> web.Response:
        """Coordination-plane health (docs/robustness.md degraded mode):
        CONNECTED/DEGRADED/RECOVERING state, probe-failure streak,
        outage accounting, frozen census events, the held-action log,
        and the client's reconnect counter — one page answering "is the
        fleet serving through a coordination outage right now, and what
        is being held back"."""
        return web.json_response(self.scheduler.coordination_health.report())

    async def handle_overload(self, request: web.Request) -> web.Response:
        """Overload-hardening plane state (docs/robustness.md): the
        admission gate (watermarks, pending, shed counts/rate), the
        brownout controller (state + transition log with reasons), the
        global retry budget, and every instance channel's circuit
        breaker — one page answering "what is being degraded, shed or
        fenced off right now, and why"."""
        snap = self.scheduler.instance_mgr.routing_snapshot()
        breakers = {}
        for name, ch in snap.channels.items():
            br = getattr(ch, "breaker", None)
            if br is not None:
                breakers[name] = br.snapshot()
        return web.json_response({
            "deadline": {
                "default_request_deadline_ms":
                    self.opts.default_request_deadline_ms,
                "request_timeout_s": self.opts.request_timeout_s,
            },
            "admission": ADMISSION.report(),
            "brownout": BROWNOUT.report(),
            "retry_budget": RETRY_BUDGET.report(),
            "breakers": breakers,
        })

    async def handle_hotpath(self, request: web.Request) -> web.Response:
        """Per-stage master hot-path latency table (always-on recorder,
        common/hotpath.py): schedule / enrich / forward / first_delta
        percentiles over the recent sample window, plus the multi-master
        plane's view — ownership/mining stats and the load-info
        telemetry ages staleness-aware scoring discounts by."""
        mgr = self.scheduler.instance_mgr
        return web.json_response({
            "stages": HOTPATH.summary(),
            # Per-category CPU attribution (ingest = heartbeat/telemetry,
            # route = schedule, stream = delta ingest): the bench's
            # ingest-share evidence for the sharded telemetry plane.
            "cpu": CPU_ATTR.summary(),
            # Where recent requests' TTFT went, stage by stage: the
            # critical-path aggregate over this process's span ring
            # (per-request decomposition: /admin/trace?request_id=...).
            "critical_path": aggregate_critical_paths(
                critical_path(spans)
                for spans in TRACER.store.recent_trace_spans(50)),
            "ownership": self.scheduler.ownership.stats(),
            # Telemetry-ingest shard map + frame-log progress + the
            # per-instance load-info snapshot ages (ISSUE 15 satellite:
            # observable, not inferred).
            "telemetry": mgr.stats(),
            "handoff_journal": self._journal.stats(),
            "snapshot_age_s": mgr.snapshot_age_s(),
            "frame_log_seq": self.scheduler.kvcache_mgr.frame_log_seq(),
            "loadinfo": {
                "ages_s": mgr.load_info_ages_s(),
                "stale": sorted(mgr.stale_load_names()),
                "stale_after_s": self.opts.loadinfo_stale_after_s,
            },
        })

    async def handle_get_faults(self, request: web.Request) -> web.Response:
        """Inspect the deterministic fault-injection plane (rules + hit/fire
        counters)."""
        from ..common.faults import FAULTS

        return web.json_response({
            "seed": FAULTS.seed,
            "rules": [r.to_dict() for r in FAULTS.rules()]})

    async def handle_set_faults(self, request: web.Request) -> web.Response:
        """Configure the fault plane: `{"rules": [...], "seed": N}` replaces
        the rule set (seeded → deterministic), `{"clear": true}` disarms."""
        from ..common.faults import FAULTS

        try:
            body = await request.json()
        except json.JSONDecodeError:
            return _error_response(400, "invalid JSON")
        if not isinstance(body, dict):
            return _error_response(400, "request body must be a JSON object")
        if body.get("clear"):
            FAULTS.clear()
        if body.get("rules") is not None:
            try:
                FAULTS.configure(body["rules"], seed=body.get("seed"))
            except (TypeError, ValueError) as e:
                return _error_response(400, f"bad fault rule: {e}")
        return web.json_response({
            "ok": True, "seed": FAULTS.seed,
            "rules": [r.to_dict() for r in FAULTS.rules()]})

    async def handle_set_config(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return _error_response(400, "invalid JSON")
        applied = {}
        for key, value in (body or {}).items():
            caster = self._RELOADABLE.get(key)
            if caster is None:
                return _error_response(
                    400, f"{key} is not live-reloadable "
                         f"(reloadable: {sorted(self._RELOADABLE)})")
            try:
                cast_value = caster(value)
            except (TypeError, ValueError):
                return _error_response(400, f"bad value for {key}")
            if key.startswith("target_") and cast_value <= 0:
                return _error_response(400, f"{key} must be positive")
            setattr(self.opts, key, cast_value)
            applied[key] = cast_value
        if "enable_tracing" in applied:
            # Live span-tracing toggle (e.g. shed the overhead under a
            # traffic spike without a restart).
            TRACER.configure(enabled=self.opts.enable_tracing)
        if "trace_sample_rate" in applied:
            # Live sampling knob: dial down under a traffic spike without
            # losing anomalies (tail-based keep still promotes them).
            TRACER.configure(sample_rate=self.opts.trace_sample_rate)
        return web.json_response({"ok": True, "applied": applied})

    # ----------------------------------------------------------- RPC routes
    async def handle_handoff(self, request: web.Request) -> web.StreamResponse:
        """Owner-side ingest of a request relayed by another frontend
        (multimaster/handoff.py): run the FULL local pipeline — schedule,
        dispatch, failover bookkeeping, trace assembly — under the
        relay-supplied service id. Never re-relays: the accepting
        frontend resolved ownership, and re-resolving here on a
        membership race could loop. The response (SSE frames or one JSON
        document) streams back to the relay, which copies it to the
        client — dropping the already-delivered frame prefix on a
        re-owned replay."""
        sid = request.query.get("sid", "")
        kind = request.query.get("kind", "completion")
        if not sid:
            return _error_response(400, "missing sid")
        try:
            attempt = int(request.query.get("attempt", 0))
            skip = int(request.query.get("skip", 0))
        except (TypeError, ValueError):
            attempt, skip = 0, 0
        if attempt > 0:
            # Relay reconnect: if THIS owner journaled the stream (the
            # relay retries the same owner first), replay the exact
            # recorded frames after `skip` — no pipeline re-run, so the
            # continuation is identical even under temperature>0
            # sampling. No journal (we are the rendezvous successor of a
            # dead owner) → fall through to the legacy full re-run with
            # relay-side frame dropping.
            entry = self._journal.get(sid)
            if entry is not None:
                HANDOFF_JOURNAL_REPLAYS_TOTAL.inc()
                return await self._replay_from_journal(request, sid, skip,
                                                       entry)
        HANDOFF_SERVED_TOTAL.inc()
        # The relay forwards the ABSOLUTE deadline it computed at accept
        # (x-xllm-deadline-ms) so the owner enforces the original
        # budget, not a fresh one restarted at the relay hop.
        try:
            deadline_ms = int(request.headers.get(ABS_DEADLINE_HEADER, 0))
        except (TypeError, ValueError):
            deadline_ms = 0
        if kind == "messages":
            return await self.handle_messages(request, sid=sid,
                                              deadline_override=deadline_ms)
        if kind not in ("chat", "completion"):
            return _error_response(400, f"unknown handoff kind {kind}")
        return await self._handle_generate(request, kind, sid=sid,
                                           deadline_override=deadline_ms)

    async def _replay_from_journal(self, http_req: web.Request, sid: str,
                                   skip: int, entry) -> web.StreamResponse:
        """Serve a relay reconnect from the delta journal: stream the
        recorded frames after ``skip``, then follow the LIVE journal
        growth (the original generation keeps appending while detached)
        until the stream finishes. Pure frame copy — the engine sees
        nothing."""
        resp = web.StreamResponse()
        resp.headers["Content-Type"] = "text/event-stream"
        resp.headers["Cache-Control"] = "no-cache"
        await resp.prepare(http_req)
        i = max(0, skip)
        deadline = time.monotonic() + self.opts.request_timeout_s
        try:
            while True:
                # Keep the journal (and the detached generation's grace
                # window) alive while this replay is attached: the
                # detached _respond loop extends its deadline off
                # `touched`, so an active replay is never cancelled
                # under it mid-stream.
                entry.touched = time.monotonic()
                frames = entry.frames
                while i < len(frames):
                    await resp.write(frames[i])
                    i += 1
                if entry.finished and i >= len(entry.frames):
                    break
                if time.monotonic() > deadline:
                    break
                await asyncio.sleep(0.02)
            await resp.write_eof()
        except (ConnectionResetError, OSError):
            pass   # the relay broke again; its next attempt re-enters here
        return resp

    async def handle_handoff_abort(self, request: web.Request) -> web.Response:
        """Relay-signalled CLIENT abort of a relayed stream: the journal
        grace exists for transport blips, but a gone client must cancel
        NOW (engine capacity, exit accounting) — the relay distinguishes
        the two, this endpoint enacts it. Idempotent; unknown sids ack."""
        sid = request.query.get("sid", "")
        if not sid:
            return _error_response(400, "missing sid")
        entry = self._journal.get(sid)
        if entry is not None:
            DeltaJournal.finish(entry)
        cancelled = await asyncio.get_running_loop().run_in_executor(
            None, self.scheduler.cancel_request, sid, 499,
            "client disconnected at relay", "disconnect")
        return web.json_response({"ok": True, "cancelled": cancelled})

    async def handle_flip_hint(self, request: web.Request) -> web.Response:
        """Replica→master write-lease proxy for PD-role flips: a
        non-elected frontend's SLO policy saw a role imbalance, but the
        coordination writes a flip performs are master-only (frame-log +
        instance-key invariants). The hint lands in this master's pending
        set; its reconcile thread executes. If mastership just moved, the
        local drain re-proxies to the current master — convergent."""
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return _error_response(400, "invalid JSON")
        if not isinstance(body, dict) or not body.get("name"):
            return _error_response(400, "missing instance name")
        try:
            new_type = InstanceType.parse(body.get("type"))
        except ValueError:
            return _error_response(400, f"bad type {body.get('type')!r}")
        self.scheduler.instance_mgr.request_flip(str(body["name"]), new_type)
        return web.json_response({"ok": True,
                                  "master": self.scheduler.is_master})

    async def handle_heartbeat(self, request: web.Request) -> web.Response:
        """Per-instance heartbeat (load/latency metrics + KV-cache event
        delta). Wire is msgpack by default — KV-event block keys ride as
        raw 16 bytes instead of hex JSON strings — with the JSON path kept
        for legacy agents (agents demote themselves when a legacy master
        rejects their binary heartbeat; see EngineAgent._heartbeat_loop).
        """
        body = await request.read()
        try:
            payload = wire.decode_body(request.content_type, body)
        except ValueError:
            return _error_response(400, "invalid payload")
        if not isinstance(payload, dict):
            return _error_response(400, "invalid payload")
        known = await asyncio.get_running_loop().run_in_executor(
            None, self.scheduler.handle_instance_heartbeat, payload)
        resp: dict[str, Any] = {"ok": True, "known": known}
        owner = self.scheduler.instance_mgr.telemetry_owner_addr(
            payload.get("name", ""))
        if owner:
            # Sharded ingest: tell the engine who owns its telemetry so
            # a beat that landed here on a membership race re-routes.
            resp["owner"] = owner
        return web.json_response(resp)

    def _ingest_gens_batch(self, gens: list) -> dict[str, bool]:
        """Shared Generations-delta ingest (direct POSTs and multiplexed
        telemetry frames): parse + dispatch the whole batch in one go,
        measured into the `stream` CPU-attribution bucket."""
        with CPU_ATTR.measure("stream"):
            results: dict[str, bool] = {}
            for gen in gens:
                out = RequestOutput.from_dict(gen)
                results[out.service_request_id] = \
                    self.scheduler.handle_generation(out)
            return results

    async def handle_telemetry(self, request: web.Request) -> web.Response:
        """Multiplexed engine telemetry session (ISSUE 15): tagged
        msgpack frames on ONE keepalive connection per engine, routed to
        the engine's owning master. "hb" frames ingest like
        /rpc/heartbeat; "gens" frames carry a `dest` service address —
        ingested here when dest is us, relayed master->master otherwise
        (the fan-out the engine no longer pays: per-engine connections
        stay O(1) while masters scale). Responses carry per-dest
        delivery verdicts so the engine's per-dest retry/cancel
        machinery keeps working unchanged."""
        body = await request.read()
        try:
            payload = wire.decode_body(request.content_type, body)
        except ValueError:
            return _error_response(400, "invalid payload")
        frames = payload.get("frames") if isinstance(payload, dict) else None
        if not isinstance(frames, list):
            return _error_response(400, "invalid payload: frames required")
        loop = asyncio.get_running_loop()
        self_addr = self.scheduler.self_addr
        alive: dict[str, bool] = {}
        dest_ok: dict[str, bool] = {}
        out: dict[str, Any] = {"ok": True}
        relays: list = []
        for fr in frames:
            if not isinstance(fr, dict):
                continue
            tag = fr.get("t")
            if tag == wire.TELEMETRY_HB:
                hb = fr.get("d") or {}
                out["known"] = await loop.run_in_executor(
                    None, self.scheduler.handle_instance_heartbeat, hb)
                owner = self.scheduler.instance_mgr.telemetry_owner_addr(
                    hb.get("name", ""))
                if not owner and \
                        not self.scheduler.instance_mgr.sharded():
                    # A mux beat landed on a master-mode (funnel)
                    # service: in that fleet only the ELECTED master
                    # uploads load metrics from locally-ingested beats,
                    # so hint the engine there — otherwise its beats
                    # strand telemetry on whichever replica the
                    # rendezvous map picked (mixed-config hazard).
                    owner = await loop.run_in_executor(
                        None, self.scheduler.elected_master_addr)
                if owner:
                    out["owner"] = owner
            elif tag == wire.TELEMETRY_GENS:
                dest = fr.get("dest") or self_addr
                gens = (fr.get("d") or {}).get("gens", [])
                if dest == self_addr:
                    if len(gens) <= 32:
                        results = self._ingest_gens_batch(gens)
                    else:
                        results = await loop.run_in_executor(
                            None, self._ingest_gens_batch, gens)
                    alive.update(results)
                    dest_ok[dest] = True
                else:
                    relays.append(self._relay_gens(dest, gens))
        for dest, ok, dest_alive in await asyncio.gather(*relays):
            dest_ok[dest] = ok
            alive.update(dest_alive)
        out["alive"] = alive
        out["dest_ok"] = dest_ok
        return web.json_response(out)

    async def _relay_gens(self, dest: str,
                          gens: list) -> tuple[str, bool, dict]:
        """Master->master relay of a foreign-dest generation batch (the
        owner-side half of the multiplexed engine session). Keepalive
        pooled connections via the shared aiohttp client; a failed relay
        reports dest_ok=False so the ENGINE keeps those frames queued
        and retries — the relay itself never re-sends (delta dedup
        belongs to the per-request seq numbers)."""
        assert self._client is not None
        TELEMETRY_GENS_RELAYED_TOTAL.labels(dest=dest).inc()
        data, ctype = wire.encode_dispatch({"gens": gens},
                                           wire.WIRE_MSGPACK)
        try:
            async with self._client.post(
                    f"http://{dest}/rpc/generations", data=data,
                    headers={"Content-Type": ctype},
                    timeout=aiohttp.ClientTimeout(total=10)) as r:
                if r.status != 200:
                    return dest, False, {}
                payload = await r.json(content_type=None)
                return dest, True, dict(payload.get("alive") or {})
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
                ValueError):
            return dest, False, {}

    async def handle_generations(self, request: web.Request) -> web.Response:
        """Batched generation deltas (reference `Generations` RPC,
        `rpc_service/service.cpp:149-215`). Response tells the engine which
        requests are dead so it can stop generating them.

        This is the service plane's hottest ingest loop: the whole batch is
        parsed and dispatched in ONE executor hop (an await per delta would
        serialize the event loop against the worker pool), and the wire
        format may be msgpack (binary, the engine agent's default — the
        reference uses batched protobuf here for the same reason) or JSON.
        """
        body = await request.read()
        try:
            payload = wire.decode_body(request.content_type, body)
        except ValueError:
            return _error_response(400, "invalid payload")
        if not isinstance(payload, dict):
            return _error_response(400, "invalid payload")

        gens = list(payload.get("gens", ()))
        if len(gens) <= 32:
            # Small batch: ingest inline. handle_generation is dict work
            # under a short lock hold (formatting/SSE rides the output
            # lanes, not this handler) — an executor hop per batch costs
            # a thread wake on the first-token path for no protection.
            results = self._ingest_gens_batch(gens)
        else:
            # Big batch (engine catch-up after a stall): keep the loop
            # responsive, take the one executor hop.
            results = await asyncio.get_running_loop().run_in_executor(
                None, self._ingest_gens_batch, gens)
        return web.json_response({"ok": True, "alive": results})

    async def handle_instance_info(self, request: web.Request) -> web.Response:
        name = request.query.get("name", "")
        meta = self.scheduler.instance_mgr.get_instance_meta(name)
        if meta is None:
            return _error_response(404, f"unknown instance {name}")
        return web.json_response(json.loads(meta.to_json()))

    async def handle_prefill_list(self, request: web.Request) -> web.Response:
        metas = self.scheduler.instance_mgr.list_instances(InstanceType.PREFILL)
        return web.json_response({"instances": [m.name for m in metas]})

    async def handle_decode_list(self, request: web.Request) -> web.Response:
        metas = self.scheduler.instance_mgr.list_instances(InstanceType.DECODE)
        return web.json_response({"instances": [m.name for m in metas]})
