"""aiohttp-backed ClientConnection.

Parity: reference `StreamCallData` over brpc ProgressiveAttachment
(`common/call_data.h:87-216`): SSE headers sent early, `data: <json>\n\n`
framing, `data: [DONE]` terminator, disconnect detection surfaced to the
scheduler so engines can be cancelled.

Scheduler output lanes are plain threads; deliveries are marshaled onto the
event loop via `call_soon_threadsafe` into an asyncio queue drained by the
request handler coroutine.
"""

from __future__ import annotations

import asyncio
from typing import Any, Optional

from ..common.call_data import ClientConnection

_FINISH = object()


class AioConnection(ClientConnection):
    def __init__(self, loop: asyncio.AbstractEventLoop, stream: bool):
        self.stream = stream
        self._loop = loop
        self.queue: "asyncio.Queue[Any]" = asyncio.Queue()
        self._disconnected = False
        self.error: Optional[tuple[int, str]] = None

    # ---- called from scheduler output lanes (threads) ----
    def _put(self, item: Any) -> None:
        self._loop.call_soon_threadsafe(self.queue.put_nowait, item)

    def write(self, obj: dict[str, Any]) -> bool:
        if self._disconnected:
            return False
        self._put(("data", obj))
        return True

    def write_event(self, event: str, obj: dict[str, Any]) -> bool:
        if self._disconnected:
            return False
        self._put(("event", (event, obj)))
        return True

    def finish(self) -> bool:
        self._put((_FINISH, None))
        return not self._disconnected

    def finish_with_error(self, code: int, message: str) -> bool:
        self.error = (code, message)
        self._put(("error", (code, message)))
        return True

    def is_disconnected(self) -> bool:
        return self._disconnected

    # ---- called from the handler coroutine ----
    def mark_disconnected(self) -> None:
        self._disconnected = True

    @staticmethod
    def is_finish(tag: Any) -> bool:
        return tag is _FINISH
