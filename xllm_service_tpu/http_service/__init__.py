"""L6 HTTP API + L2 RPC endpoints (aiohttp).

Parity: reference `http_service/` + `rpc_service/` (SURVEY.md §2.2-2.3).
"""
