"""Opt-in request I/O tracing.

Parity: reference `http_service/request_tracer.{h,cpp}` — appends
`{timestamp, service_request_id, data}` JSONL under a mutex, gated by
`--enable_request_trace` (`request_tracer.cpp:38-61`).

Beyond the reference (which reopens the file for every record — an
open/append/close syscall triple per log call): the handle is opened once
and kept line-buffered (each record still lands on disk at its newline, so
live `tail -f`/test reads see records immediately, but the per-record
open/close churn is gone), with an explicit `close()`/`flush()` invoked
from service cleanup. Output is `trace.jsonl` (it always was JSONL);
a directory that already holds a legacy `trace.json` keeps appending
there so old dirs stay readable with one file to look at.
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Any, Optional, TextIO

from ..devtools.locks import make_lock


class RequestTracer:
    def __init__(self, trace_dir: str = "trace", enabled: bool = False):
        self._enabled = enabled
        self._lock = make_lock("request_tracer.file", order=70)  # lock-order: 70
        d = Path(trace_dir)
        legacy = d / "trace.json"
        self._path = legacy if legacy.exists() else d / "trace.jsonl"
        self._fh: Optional[TextIO] = None
        if enabled:
            self._path.parent.mkdir(parents=True, exist_ok=True)

    @property
    def enabled(self) -> bool:
        return self._enabled

    @property
    def path(self) -> Path:
        return self._path

    def log(self, service_request_id: str, data: Any) -> None:
        if not self._enabled:
            return
        rec = {"timestamp": int(time.time() * 1000),
               "service_request_id": service_request_id,
               "data": data}
        line = json.dumps(rec, ensure_ascii=False) + "\n"
        with self._lock:
            if self._fh is None:
                # Lazy (re)open: first record, or a straggler logged on an
                # output lane after cleanup closed the handle.
                self._fh = self._path.open("a", buffering=1)
            self._fh.write(line)

    def flush(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.flush()

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                self._fh.close()
                self._fh = None
