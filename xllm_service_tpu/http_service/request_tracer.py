"""Opt-in request I/O tracing.

Parity: reference `http_service/request_tracer.{h,cpp}` — appends
`{timestamp, service_request_id, data}` JSONL under a mutex to
`trace/trace.json`, gated by `--enable_request_trace`
(`request_tracer.cpp:38-61`).
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Any

from ..devtools.locks import make_lock


class RequestTracer:
    def __init__(self, trace_dir: str = "trace", enabled: bool = False):
        self._enabled = enabled
        self._lock = make_lock("request_tracer.file", order=70)  # lock-order: 70
        self._path = Path(trace_dir) / "trace.json"
        if enabled:
            self._path.parent.mkdir(parents=True, exist_ok=True)

    @property
    def enabled(self) -> bool:
        return self._enabled

    def log(self, service_request_id: str, data: Any) -> None:
        if not self._enabled:
            return
        rec = {"timestamp": int(time.time() * 1000),
               "service_request_id": service_request_id,
               "data": data}
        line = json.dumps(rec, ensure_ascii=False) + "\n"
        with self._lock:
            with self._path.open("a") as f:
                f.write(line)
