"""Run a standalone fake engine instance against a coordination server.

Parity with the reference's `examples/rpc_client_test.cpp` (registers a
hand-driven fake instance against a running service; SURVEY.md §2.10) —
useful for driving a real master process without TPU hardware:

    python -m xllm_service_tpu.coordination.server --port 12379 &
    python -m xllm_service_tpu.master --coordination-addr 127.0.0.1:12379 &
    python examples/run_fake_engine.py --coordination-addr 127.0.0.1:12379
"""

import argparse
import signal
import sys
import threading
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from xllm_service_tpu.common.types import InstanceType
from xllm_service_tpu.coordination import connect
from xllm_service_tpu.testing.fake_engine import FakeEngine, FakeEngineConfig


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--coordination-addr", default="127.0.0.1:12379")
    p.add_argument("--type", default="MIX",
                   choices=[t.value for t in InstanceType])
    p.add_argument("--reply", default="Hello from the fake engine!")
    p.add_argument("--model", default="fake-model")
    p.add_argument("--chunk-size", type=int, default=4,
                   help="characters per Generations delta")
    p.add_argument("--delay", type=float, default=0.0,
                   help="inter-delta delay in seconds (0 = instant; the "
                        "hot-path bench uses 0 so client TTFT isolates "
                        "the master+wire span)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="advertised port (0 = pick free; the autoscaler's "
                        "local actuator passes one so the instance name is "
                        "known at launch)")
    p.add_argument("--service-rate", type=float, default=0.0,
                   help="deterministic capacity model: serve at most "
                        "this many generations per second (0 = "
                        "unlimited); the overload/autoscaling benches' "
                        "per-engine capacity knob")
    p.add_argument("--accept-queue", type=int, default=0,
                   help="bounded accept queue in front of the service "
                        "rate (0 = unbounded); a full queue 503s")
    p.add_argument("--first-delta-delay", type=float, default=0.0,
                   help="simulated prefill latency: sleep before the "
                        "first delta of each generation")
    p.add_argument("--accept-delay", type=float, default=0.0,
                   help="DEPRECATED alias: mapped to "
                        "--service-rate 1/delay (the old blocking-"
                        "accept hack is gone)")
    p.add_argument("--heartbeat-interval", type=float, default=0.5)
    p.add_argument("--lease-ttl", type=float, default=1.0)
    p.add_argument("--telemetry-mode", default="owner",
                   choices=["owner", "mux", "master"],
                   help="owner = heartbeats to the rendezvous telemetry "
                        "owner (deltas direct); mux = heartbeats AND "
                        "deltas multiplexed on one keepalive session to "
                        "the owner; master = legacy elected-master "
                        "heartbeat funnel (the ingest-sharding bench "
                        "baseline)")
    p.add_argument("--degraded-mode", default="on", choices=["on", "off"],
                   help="on = keep heartbeats flowing to the last-known-"
                        "good master while the coordination plane is "
                        "unreachable (static stability); off = legacy "
                        "behavior (no resolvable target, no beats — the "
                        "outage bench's control leg)")
    p.add_argument("--slice-id", default="fake-slice",
                   help="TPU slice/pod coordinate; same-slice PD handoffs "
                        "are ICI-classed, cross-slice DCN "
                        "(docs/topology.md)")
    p.add_argument("--topo-host", default="",
                   help="physical host coordinate; non-empty marks this "
                        "instance PLACED for topology-aware routing "
                        "('' = legacy flat behavior)")
    p.add_argument("--topo-chip", type=int, default=-1,
                   help="chip index within --topo-host (-1 = unpinned)")
    p.add_argument("--kv-handoff-bytes-per-token", type=int, default=0,
                   help="modeled PD KV payload per prompt token: split-"
                        "pair dispatches sleep the link-classed wire "
                        "time before the first delta (0 = no modeled "
                        "handoff — the topo bench's load-bearing knob)")
    p.add_argument("--ici-bytes-per-s", type=float, default=0.0,
                   help="modeled ICI bandwidth for the handoff sleep "
                        "(0 = class default)")
    p.add_argument("--dcn-bytes-per-s", type=float, default=0.0,
                   help="modeled DCN bandwidth for the handoff sleep "
                        "(0 = class default)")
    args = p.parse_args()

    rate = max(0.0, args.service_rate)
    if not rate and args.accept_delay > 0:
        rate = 1.0 / args.accept_delay
    coord = connect(args.coordination_addr)
    engine = FakeEngine(coord, FakeEngineConfig(
        instance_type=InstanceType.parse(args.type),
        models=[args.model], reply_text=args.reply,
        chunk_size=max(1, args.chunk_size), delay_s=max(0.0, args.delay),
        host=args.host, port=max(0, args.port),
        service_rate_rps=rate,
        accept_queue_limit=max(0, args.accept_queue),
        first_delta_delay_s=max(0.0, args.first_delta_delay),
        heartbeat_interval_s=max(0.05, args.heartbeat_interval),
        lease_ttl_s=max(0.2, args.lease_ttl),
        telemetry_mode=args.telemetry_mode,
        degraded_mode=args.degraded_mode,
        slice_id=args.slice_id,
        topo_host=args.topo_host,
        topo_chip=args.topo_chip,
        kv_handoff_bytes_per_token=max(0, args.kv_handoff_bytes_per_token),
        ici_bytes_per_s=max(0.0, args.ici_bytes_per_s),
        dcn_bytes_per_s=max(0.0, args.dcn_bytes_per_s))
    ).start()
    print(f"fake engine {engine.name} ({args.type}) registered; Ctrl-C to stop",
          flush=True)
    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    engine.stop()
    coord.close()


if __name__ == "__main__":
    main()
