#!/bin/sh
# Parity with reference examples/curl_http_client.sh
curl -s "${1:-http://127.0.0.1:18888}/hello"
