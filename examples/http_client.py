"""HTTP client driver: stream + non-stream completions against a running
service (parity with reference `examples/http_client_test.cpp`).

    python examples/http_client.py --base http://127.0.0.1:18888
"""

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import requests


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--base", default="http://127.0.0.1:18888")
    p.add_argument("--model", default="")
    p.add_argument("--prompt", default="Tell me a story about TPUs.")
    p.add_argument("--max-tokens", type=int, default=64)
    args = p.parse_args()

    model = args.model
    if not model:
        models = requests.get(args.base + "/v1/models", timeout=10).json()
        model = models["data"][0]["id"] if models.get("data") else "default"

    print("== non-stream ==")
    r = requests.post(args.base + "/v1/completions", json={
        "model": model, "prompt": args.prompt,
        "max_tokens": args.max_tokens}, timeout=300)
    print(json.dumps(r.json(), indent=2)[:1000])

    print("\n== stream ==")
    r = requests.post(args.base + "/v1/chat/completions", json={
        "model": model, "stream": True,
        "stream_options": {"include_usage": True},
        "messages": [{"role": "user", "content": args.prompt}],
        "max_tokens": args.max_tokens}, stream=True, timeout=300)
    for line in r.iter_lines():
        if not line.startswith(b"data: "):
            continue
        payload = line[6:]
        if payload == b"[DONE]":
            print("\n[DONE]")
            break
        chunk = json.loads(payload)
        if chunk.get("choices"):
            delta = chunk["choices"][0].get("delta", {})
            sys.stdout.write(delta.get("content") or
                             chunk["choices"][0].get("text") or "")
            sys.stdout.flush()
        elif chunk.get("usage"):
            print(f"\nusage: {chunk['usage']}")


if __name__ == "__main__":
    main()
