"""Benchmark harness: steady-state decode throughput on real TPU.

Measures the engine's hot path — the jit decode step (paged attention +
sampling) at full batch — on whatever accelerator is attached, and prints
ONE JSON line:

    {"metric": "decode_tokens_per_sec_per_chip", "value": N,
     "unit": "tok/s", "vs_baseline": R, "pct_roofline": P, ...}

Baselines (the reference publishes no numbers — BASELINE.md):
- ``vs_baseline``: measured throughput relative to the BEST PRIOR MEASURED
  run at the same bench config (BEST_PRIOR below; round-2 driver sweep,
  tpu_results/bench.json). >1.0 means this round got faster. Configs with
  no prior measurement fall back to the reference scheduler's SLO-implied
  rate (50 ms TPOT default, `global_gflags.cpp:128-132` → B/0.05 tok/s),
  labeled via "baseline_kind".
- ``pct_roofline``: decode at serving batch is HBM-bandwidth-bound, so the
  honest ceiling is the weight+KV stream per token-step against the chip's
  HBM bandwidth (v5e ≈ 819 GB/s). Reported as % of that ceiling, with
  bytes-moved/step alongside, so "fast" is falsifiable (VERDICT r2 weak #3).

Model selection: XLLM_BENCH_MODEL=1b (default) | 8b | moe — 8b is
Llama-3-8B shapes (BASELINE config 1), moe is the MLA+MoE bench shape
(BASELINE config 4 datum); both force weight-only int8 unless
XLLM_QUANT is set explicitly (bf16 doesn't fit / leaves no KV headroom
on the 16 GB v5e).
"""

from __future__ import annotations

import json
import time

import numpy as np

METRIC = "decode_tokens_per_sec_per_chip"

# Seed best-prior rows for artifacts that predate the self-maintained
# history (round-2 driver sweep; those artifacts lacked "model"/"quant"
# fields). Everything newer is discovered by _best_prior() scanning
# BENCH_r*.json + tpu_results/ + tpu_results/history.jsonl, so this dict
# never needs hand-maintenance again (VERDICT r3 weak #6).
_SEED_PRIOR = {
    # Exact round-2 sweep values: the sweep's shell redirect truncates an
    # arm's own artifact before bench.py starts, so a record stored ONLY
    # in that file is invisible to that arm's re-run — the seed (and,
    # for everything after round 4, history.jsonl) must carry it.
    ("1b", ""): 1091.4,
    ("1b", "int8"): 1077.83,
}

HISTORY = "tpu_results/history.jsonl"


def _candidate_records(obj):
    """Pull bench-record dicts out of an artifact of any known shape:
    a plain record, a driver wrapper ({"parsed": record, ...}), or a
    history.jsonl line."""
    if not isinstance(obj, dict):
        return
    if obj.get("metric") == METRIC:
        yield obj
    parsed = obj.get("parsed")
    if isinstance(parsed, dict) and parsed.get("metric") == METRIC:
        yield parsed


def _iter_prior_records(root: str | None = None):
    """Yield every prior on-chip bench record we can find on disk.

    Covers BENCH_r*.json (driver wrapper objects, pretty-printed — parse
    the whole file, read the nested "parsed" record), tpu_results/
    bench*.json (one record per file), and tpu_results/history.jsonl
    (one record per line, appended by _append_history)."""
    import glob
    import os
    here = root or os.path.dirname(os.path.abspath(__file__))
    paths = (glob.glob(os.path.join(here, "BENCH_r*.json"))
             + glob.glob(os.path.join(here, "tpu_results", "bench*.json"))
             + [os.path.join(here, HISTORY)])
    for p in paths:
        try:
            with open(p) as f:
                text = f.read()
        except OSError:
            continue
        try:
            objs = [json.loads(text)]
        except ValueError:
            # jsonl (history) / partial artifact: scan per line.
            objs = []
            for ln in text.splitlines():
                try:
                    objs.append(json.loads(ln))
                except ValueError:
                    continue
        for obj in objs:
            for rec in _candidate_records(obj):
                if (rec.get("backend") == "tpu"
                        and not rec.get("error")
                        and rec.get("value", 0) > 0):
                    yield rec


def _bench_variant() -> str:
    """Non-default kernel/route knobs that change what bench.py measures.
    Kept in the record (and matched by _best_prior) so A/B sweep arms
    (fused/scatter writeback, pallas prefill) don't contaminate the
    default config's best-prior baseline."""
    import os
    parts = []
    wb = os.environ.get("XLLM_KV_WRITEBACK", "")
    if wb:
        parts.append(f"wb={wb}")
    if os.environ.get("XLLM_PREFILL_PALLAS", ""):
        parts.append("prefill_pallas")
    if os.environ.get("XLLM_MQ_PALLAS", ""):
        parts.append("mq_pallas")
    pc = os.environ.get("XLLM_PAGE_CHUNK", "")
    if pc:
        parts.append(f"chunk={pc}")
    if os.environ.get("XLLM_PAGE_PIPELINE", "") == "row":
        parts.append("rowpipe")
    return ",".join(parts)


def _best_tpu(model_key: str, quant: str, variant: str,
              root: str | None = None) -> dict | None:
    """Best prior MEASURED on-chip record at this (model, quant, variant)
    bench config, discovered from disk artifacts rather than a
    hand-edited dict. Returns {"value": tok/s, "ts": iso-date?} — the
    high-water mark the bench baselines against, with the winning run's
    timestamp when its record carries one (history rows do; the seed
    and round-2 artifacts don't)."""
    best = _SEED_PRIOR.get((model_key, quant)) if not variant else None
    ts = None
    for rec in _iter_prior_records(root):
        if (rec.get("model", "1b") == model_key
                and rec.get("quant", "") == quant
                and rec.get("variant", "") == variant):
            v = float(rec["value"])
            if best is None or v > best:
                best, ts = v, rec.get("ts")
    if best is None:
        return None
    out = {"value": best, "model": model_key}
    if quant:
        out["quant"] = quant
    if variant:
        out["variant"] = variant
    if ts:
        out["ts"] = ts
    return out


def _best_prior(model_key: str, quant: str, variant: str,
                root: str | None = None) -> float | None:
    rec = _best_tpu(model_key, quant, variant, root)
    return rec["value"] if rec else None


def _append_history(result: dict) -> None:
    """Record this run so future rounds' vs_baseline is self-maintaining."""
    import os
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        os.makedirs(os.path.join(here, "tpu_results"), exist_ok=True)
        rec = dict(result)
        rec.setdefault("ts", time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                           time.gmtime()))
        with open(os.path.join(here, HISTORY), "a") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError:
        pass



HBM_GBPS = {"tpu": 819.0}   # v5e HBM bandwidth ceiling (public spec)


def _fail(err: str, backend: str = "") -> None:
    """Emit the structured one-line JSON contract even on hard failure
    (dead TPU relay, backend init error) instead of dying rc!=0."""
    out = {"metric": METRIC, "value": 0.0, "unit": "tok/s",
           "vs_baseline": 0.0, "error": err[:500]}
    if backend:
        out["backend"] = backend
    print(json.dumps(out))


def _accel_alive(timeout_s: float = 150.0) -> bool:
    """Probe accelerator init in a subprocess with a hard timeout.

    A dead remote-TPU relay makes in-process `jax.devices()` hang far past
    any driver timeout (round-1 MULTICHIP rc=124 was exactly this), so
    never attempt first init in this process.
    """
    import subprocess
    import sys
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; assert jax.default_backend() != 'cpu'"],
            timeout=timeout_s, capture_output=True)
        return r.returncode == 0
    except Exception:  # noqa: BLE001 — timeout or spawn failure
        return False


def _pin_cpu() -> None:
    import os
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    os.environ["JAX_PLATFORMS"] = "cpu"


def main() -> None:
    import os
    tpu_note = None
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        tpu_note = "CPU requested via env"
        _pin_cpu()
    elif not _accel_alive():
        tpu_note = "accelerator unreachable; measured on CPU fallback"
        _pin_cpu()
    try:
        import jax
        import jax.numpy as jnp
        if tpu_note:
            jax.config.update("jax_platforms", "cpu")
        backend = jax.default_backend()
    except Exception as e:  # noqa: BLE001 — any backend-init failure
        _fail(f"jax backend init failed: {type(e).__name__}: {e}")
        return

    import sys
    if "--compile-only" in sys.argv:
        # Mosaic compile gate (VERDICT r4 next #6): AOT-compile every
        # Pallas kernel arm and report per-arm verdicts without timing
        # anything. Shares this function's backend setup so the CPU
        # fallback/pinning behavior is identical to a timing run.
        import importlib.util
        gate_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "benchmarks", "compile_gate.py")
        spec = importlib.util.spec_from_file_location("compile_gate",
                                                      gate_path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        result = mod.run_gate()
        if tpu_note:
            result["note"] = tpu_note
        print(json.dumps(result))
        return

    from xllm_service_tpu.engine.config import EngineConfig
    from xllm_service_tpu.engine.engine import InferenceEngine
    from xllm_service_tpu.models.base import (bench_1b_config,
                                              llama3_8b_config, tiny_config)

    on_accel = backend not in ("cpu",)
    model_key = os.environ.get("XLLM_BENCH_MODEL", "1b") if on_accel else "1b"
    # 8b and moe default to weight-only int8 (bf16 doesn't fit/leaves no
    # KV headroom on a 16 GB chip).
    quant = os.environ.get("XLLM_QUANT",
                           "int8" if model_key in ("8b", "moe") else "")
    if model_key == "8b":
        mcfg = llama3_8b_config()
    elif model_key == "moe":
        from xllm_service_tpu.models.deepseek_moe import bench_moe_config
        mcfg = bench_moe_config()
    elif on_accel:
        mcfg = bench_1b_config()
    else:
        mcfg = tiny_config(dtype=jnp.float32)
    if quant:
        import dataclasses

        mcfg = dataclasses.replace(mcfg, quant=quant)

    B = 16 if on_accel else 8
    ctx = 512 if on_accel else 64
    max_seq = 1024 if on_accel else 128
    ctx_variant = ""
    if on_accel and os.environ.get("XLLM_BENCH_CTX", ""):
        # Long-context decode variant: the page walk dominates here, so
        # this is where the paged-kernel/DMA knobs actually show.
        # Batch shrinks to keep the KV pool inside one chip's HBM.
        try:
            ctx_req = int(os.environ["XLLM_BENCH_CTX"])
        except ValueError:
            # The contract is one JSON line even on bad input.
            _fail(f"bad XLLM_BENCH_CTX "
                  f"{os.environ['XLLM_BENCH_CTX']!r}", backend)
            return
        if ctx_req + 512 > mcfg.max_context_len:
            # 16k-32k arms (VERDICT r4 next #7): widen the model's rope
            # window to fit the requested context — same weights/shapes
            # otherwise, so the paged-walk depth is the only variable.
            import dataclasses as _dc
            mcfg = _dc.replace(mcfg, max_context_len=ctx_req + 512)
        ctx = min(ctx_req, mcfg.max_context_len - 512)
        B = (16 if ctx <= 512 else 8 if ctx <= 1024 else
             4 if ctx <= 4096 else 2 if ctx <= 16384 else 1)
        max_seq = ctx + 512
        # Label with the EFFECTIVE ctx (the request may have been
        # clamped) so baseline rows key to shapes actually measured.
        ctx_variant = f"ctx={ctx}"
    cfg = EngineConfig(
        model_id=f"bench-{model_key}", model=mcfg,
        model_family=mcfg.name,
        num_pages=(B * max_seq) // 16 + 64, page_size=16,
        max_batch_size=B, max_seq_len=max_seq,
        prefill_buckets=(128, 512, max_seq) if on_accel else (64, 128),
        hash_block_size=128 if on_accel else 32,
        decode_horizon=32 if on_accel else 4)

    rng = np.random.default_rng(0)
    prompts = [rng.integers(10, mcfg.vocab_size - 10, ctx).tolist()
               for _ in range(B)]

    from xllm_service_tpu.common.request import SamplingParams
    from xllm_service_tpu.engine.engine import EngineRequest

    counts = {"tokens": 0}

    def on_output(out):
        counts["tokens"] += sum(len(s.token_ids) for s in out.outputs)

    admit_deadline = time.perf_counter() + 600
    try:
        engine = InferenceEngine(cfg)
        # Admit all B sequences (prefill) — not timed; we measure decode.
        for i, p in enumerate(prompts):
            engine.submit(EngineRequest(
                f"bench-{i}", token_ids=p,
                sampling=SamplingParams(max_tokens=max_seq - ctx - 8,
                                        temperature=0.0, ignore_eos=True),
                on_output=on_output))
        while engine._waiting or len(engine._running) < B:
            engine.step()
            if not engine._waiting and engine._running:
                break
            if time.perf_counter() > admit_deadline:
                _fail("admission stalled", backend)
                return

        # Warmup decode steps (compile + cache).
        for _ in range(2):
            engine.step()

        n_steps = 10 if on_accel else 4   # horizons (tokens/step = horizon)
        start = counts["tokens"]
        t0 = time.perf_counter()
        for _ in range(n_steps):
            engine.step()
        dt = time.perf_counter() - t0
        generated = counts["tokens"] - start
    except Exception as e:  # noqa: BLE001 — mid-run device/tunnel failure
        _fail(f"bench run failed: {type(e).__name__}: {e}", backend)
        return

    toks_per_s = generated / dt

    # CPU fallback runs tiny_config — no prior-measured row applies there.
    variant = ",".join(p for p in (_bench_variant(), ctx_variant) if p)
    best_prior = (_best_prior(model_key, mcfg.quant, variant)
                  if on_accel else None)
    if best_prior:
        baseline, baseline_kind = best_prior, "best_prior_measured"
    else:
        # No prior on-chip measurement at this config: reference default
        # TPOT SLO (50 ms/token at batch B).
        baseline, baseline_kind = B / 0.050, "slo_implied"

    # Roofline: HBM bytes per decode token-step = one full weight stream
    # + per-sequence KV read at the mid-run context length.
    mid_ctx = ctx + (generated // (2 * B)) if B else ctx
    bytes_per_tok_step = (mcfg.decode_weight_stream_bytes()
                          + B * mcfg.kv_bytes_per_token(mid_ctx))
    tok_steps_per_s = toks_per_s / B   # B tokens per token-step
    eff_gbps = bytes_per_tok_step * tok_steps_per_s / 1e9
    hbm = HBM_GBPS.get(backend)

    result = {
        "metric": METRIC,
        "value": round(toks_per_s, 2),
        "unit": "tok/s",
        "vs_baseline": round(toks_per_s / baseline, 3),
        "baseline_kind": baseline_kind,
        "backend": backend,
        "model": model_key,
        "bytes_per_step_mb": round(bytes_per_tok_step / 1e6, 1),
        "effective_gbps": round(eff_gbps, 1),
    }
    if hbm:
        result["pct_roofline"] = round(100.0 * eff_gbps / hbm, 1)
    if tpu_note:
        # A fallback number drifts with host load (measured spread on this
        # box: 4195-5559 tok/s across back-to-back runs) and with code
        # shape (the loop is tuned for device-compute overlap that a
        # 1-CPU box can't express). Mark it structural-only and carry the
        # best real on-chip figure for the REQUESTED config so the
        # deliverable metric is never silently replaced by noise.
        result["note"] = tpu_note
        result["structural_only"] = True
        req_model = os.environ.get("XLLM_BENCH_MODEL", "1b")
        req_quant = os.environ.get(
            "XLLM_QUANT", "int8" if req_model in ("8b", "moe") else "")
        # Key the lookup exactly the way an on-chip run of the REQUESTED
        # config would have labeled itself: on this path ctx_variant was
        # never computed (tiny_config was forced), so append the
        # effective (clamp-adjusted) ctx of the requested model to the
        # knob variant already in `variant`. A malformed ctx env must not
        # break the emit-JSON-even-on-failure contract.
        req_variant = variant
        try:
            req_ctx = int(os.environ.get("XLLM_BENCH_CTX", ""))
        except ValueError:
            req_ctx = 0
        if req_ctx:
            # Effective ctx == requested (the on-accel path widens the
            # model's context window rather than clamping).
            req_variant = ",".join(
                p for p in (req_variant, f"ctx={req_ctx}") if p)
        best = _best_tpu(req_model, req_quant, req_variant)
        if best:
            result["best_tpu"] = best
    if mcfg.quant:
        result["quant"] = mcfg.quant
    if variant:
        result["variant"] = variant
    if on_accel:
        _append_history(result)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
