"""Benchmark harness: steady-state decode throughput on real TPU.

Measures the engine's hot path — the jit decode step (paged attention +
sampling) at full batch — on whatever accelerator is attached, and prints
ONE JSON line:

    {"metric": "decode_tokens_per_sec_per_chip", "value": N,
     "unit": "tok/s", "vs_baseline": R}

Baseline: the reference publishes no numbers (BASELINE.md); its scheduler's
default decode SLO is 50 ms TPOT (`global_gflags.cpp:128-132`), i.e.
batch_size/0.05 tok/s/instance at the bench batch size. vs_baseline is
measured throughput relative to that SLO-implied rate — >1.0 means every
token beats the reference's default TPOT target at full batch.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def main() -> None:
    from xllm_service_tpu.engine.config import EngineConfig
    from xllm_service_tpu.engine.engine import InferenceEngine
    from xllm_service_tpu.models.base import bench_1b_config, tiny_config

    backend = jax.default_backend()
    on_accel = backend not in ("cpu",)
    mcfg = bench_1b_config() if on_accel else tiny_config(dtype=jnp.float32)

    B = 16 if jax.default_backend() != "cpu" else 8
    ctx = 512 if on_accel else 64
    max_seq = 1024 if on_accel else 128
    cfg = EngineConfig(
        model_id="bench-1b", model=mcfg,
        num_pages=(B * max_seq) // 16 + 64, page_size=16,
        max_batch_size=B, max_seq_len=max_seq,
        prefill_buckets=(128, 512, max_seq) if on_accel else (64, 128),
        hash_block_size=128 if on_accel else 32,
        decode_horizon=32 if on_accel else 4)
    engine = InferenceEngine(cfg)

    rng = np.random.default_rng(0)
    prompts = [rng.integers(10, mcfg.vocab_size - 10, ctx).tolist()
               for _ in range(B)]

    from xllm_service_tpu.common.request import SamplingParams
    from xllm_service_tpu.engine.engine import EngineRequest

    counts = {"tokens": 0}

    def on_output(out):
        counts["tokens"] += sum(len(s.token_ids) for s in out.outputs)

    # Admit all B sequences (prefill) — not timed; we measure decode.
    for i, p in enumerate(prompts):
        engine.submit(EngineRequest(
            f"bench-{i}", token_ids=p,
            sampling=SamplingParams(max_tokens=max_seq - ctx - 8,
                                    temperature=0.0, ignore_eos=True),
            on_output=on_output))
    admit_deadline = time.perf_counter() + 600
    while engine._waiting or len(engine._running) < B:
        engine.step()
        if not engine._waiting and engine._running:
            break
        if time.perf_counter() > admit_deadline:
            print(json.dumps({"metric": "decode_tokens_per_sec_per_chip",
                              "value": 0.0, "unit": "tok/s",
                              "vs_baseline": 0.0,
                              "error": "admission stalled"}))
            return

    # Warmup decode steps (compile + cache).
    for _ in range(2):
        engine.step()

    n_steps = 10 if on_accel else 4   # horizons (tokens = steps * horizon)
    start = counts["tokens"]
    t0 = time.perf_counter()
    for _ in range(n_steps):
        engine.step()
    dt = time.perf_counter() - t0
    generated = counts["tokens"] - start

    toks_per_s = generated / dt
    baseline = B / 0.050   # reference default TPOT SLO: 50ms/token at batch B
    print(json.dumps({
        "metric": "decode_tokens_per_sec_per_chip",
        "value": round(toks_per_s, 2),
        "unit": "tok/s",
        "vs_baseline": round(toks_per_s / baseline, 3),
    }))


if __name__ == "__main__":
    main()
