"""Benchmark harness: steady-state decode throughput on real TPU.

Measures the engine's hot path — the jit decode step (paged attention +
sampling) at full batch — on whatever accelerator is attached, and prints
ONE JSON line:

    {"metric": "decode_tokens_per_sec_per_chip", "value": N,
     "unit": "tok/s", "vs_baseline": R}

Baseline: the reference publishes no numbers (BASELINE.md); its scheduler's
default decode SLO is 50 ms TPOT (`global_gflags.cpp:128-132`), i.e.
batch_size/0.05 tok/s/instance at the bench batch size. vs_baseline is
measured throughput relative to that SLO-implied rate — >1.0 means every
token beats the reference's default TPOT target at full batch.
"""

from __future__ import annotations

import json
import time

import numpy as np

METRIC = "decode_tokens_per_sec_per_chip"


def _fail(err: str) -> None:
    """Emit the structured one-line JSON contract even on hard failure
    (dead TPU relay, backend init error) instead of dying rc!=0."""
    print(json.dumps({"metric": METRIC, "value": 0.0, "unit": "tok/s",
                      "vs_baseline": 0.0, "error": err[:500]}))


def _accel_alive(timeout_s: float = 150.0) -> bool:
    """Probe accelerator init in a subprocess with a hard timeout.

    A dead remote-TPU relay makes in-process `jax.devices()` hang far past
    any driver timeout (round-1 MULTICHIP rc=124 was exactly this), so
    never attempt first init in this process.
    """
    import subprocess
    import sys
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; assert jax.default_backend() != 'cpu'"],
            timeout=timeout_s, capture_output=True)
        return r.returncode == 0
    except Exception:  # noqa: BLE001 — timeout or spawn failure
        return False


def _pin_cpu() -> None:
    import os
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    os.environ["JAX_PLATFORMS"] = "cpu"


def main() -> None:
    import os
    tpu_note = None
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        tpu_note = "CPU requested via env"
        _pin_cpu()
    elif not _accel_alive():
        tpu_note = "accelerator unreachable; measured on CPU fallback"
        _pin_cpu()
    try:
        import jax
        import jax.numpy as jnp
        if tpu_note:
            jax.config.update("jax_platforms", "cpu")
        backend = jax.default_backend()
    except Exception as e:  # noqa: BLE001 — any backend-init failure
        _fail(f"jax backend init failed: {type(e).__name__}: {e}")
        return

    from xllm_service_tpu.engine.config import EngineConfig
    from xllm_service_tpu.engine.engine import InferenceEngine
    from xllm_service_tpu.models.base import bench_1b_config, tiny_config

    on_accel = backend not in ("cpu",)
    mcfg = bench_1b_config() if on_accel else tiny_config(dtype=jnp.float32)
    if os.environ.get("XLLM_QUANT") == "int8":
        import dataclasses

        mcfg = dataclasses.replace(mcfg, quant="int8")

    B = 16 if on_accel else 8
    ctx = 512 if on_accel else 64
    max_seq = 1024 if on_accel else 128
    cfg = EngineConfig(
        model_id="bench-1b", model=mcfg,
        num_pages=(B * max_seq) // 16 + 64, page_size=16,
        max_batch_size=B, max_seq_len=max_seq,
        prefill_buckets=(128, 512, max_seq) if on_accel else (64, 128),
        hash_block_size=128 if on_accel else 32,
        decode_horizon=32 if on_accel else 4)

    rng = np.random.default_rng(0)
    prompts = [rng.integers(10, mcfg.vocab_size - 10, ctx).tolist()
               for _ in range(B)]

    from xllm_service_tpu.common.request import SamplingParams
    from xllm_service_tpu.engine.engine import EngineRequest

    counts = {"tokens": 0}

    def on_output(out):
        counts["tokens"] += sum(len(s.token_ids) for s in out.outputs)

    admit_deadline = time.perf_counter() + 600
    try:
        engine = InferenceEngine(cfg)
        # Admit all B sequences (prefill) — not timed; we measure decode.
        for i, p in enumerate(prompts):
            engine.submit(EngineRequest(
                f"bench-{i}", token_ids=p,
                sampling=SamplingParams(max_tokens=max_seq - ctx - 8,
                                        temperature=0.0, ignore_eos=True),
                on_output=on_output))
        while engine._waiting or len(engine._running) < B:
            engine.step()
            if not engine._waiting and engine._running:
                break
            if time.perf_counter() > admit_deadline:
                _fail("admission stalled")
                return

        # Warmup decode steps (compile + cache).
        for _ in range(2):
            engine.step()

        n_steps = 10 if on_accel else 4   # horizons (tokens/step = horizon)
        start = counts["tokens"]
        t0 = time.perf_counter()
        for _ in range(n_steps):
            engine.step()
        dt = time.perf_counter() - t0
        generated = counts["tokens"] - start
    except Exception as e:  # noqa: BLE001 — mid-run device/tunnel failure
        _fail(f"bench run failed: {type(e).__name__}: {e}")
        return

    toks_per_s = generated / dt
    baseline = B / 0.050   # reference default TPOT SLO: 50ms/token at batch B
    result = {
        "metric": METRIC,
        "value": round(toks_per_s, 2),
        "unit": "tok/s",
        "vs_baseline": round(toks_per_s / baseline, 3),
        "backend": backend,
    }
    if tpu_note:
        result["note"] = tpu_note
    if mcfg.quant:
        result["quant"] = mcfg.quant
    print(json.dumps(result))


if __name__ == "__main__":
    main()
