#!/usr/bin/env bash
# Static-analysis gate: xlint (project concurrency invariants, always) +
# ruff (generic lint, when installed). CI runs the same xlint pass via
# tests/test_xlint.py::test_xlint_tree_clean. Tier-1 tests run separately
# via scripts/tier1.sh (the canonical 3-chunk split).
set -euo pipefail
cd "$(dirname "$0")/.."

# One xlint invocation per profile, consumed as --format json: stable
# exit codes (0 clean / 1 violations / 2 usage), machine-readable
# violation list, file counts from the single shared parse.
run_xlint() {
    local label="$1"; shift
    local out rc=0
    out=$(python -m xllm_service_tpu.devtools.xlint --format json "$@") \
        || rc=$?
    if [ "$rc" -eq 0 ]; then
        echo "$out" | python -c 'import json, sys
d = json.load(sys.stdin)
print("xlint: clean (%d files, %s profile)" % (d["files"], d["profile"]))'
        return 0
    fi
    echo "$out" | python -c 'import json, sys
d = json.load(sys.stdin)
for v in d["violations"]:
    print("%s:%d: [%s] %s" % (v["path"], v["line"], v["rule"], v["message"]))
print("xlint: %d violation(s)" % d["count"])' 2>/dev/null \
        || echo "$out"
    return "$rc"
}

echo "== xlint (concurrency + RCU + state-ownership invariants) =="
run_xlint strict xllm_service_tpu

echo "== xlint --support (tests/ + benchmarks/, relaxed profile) =="
run_xlint support --support tests benchmarks

echo "== bench trend (headline-metric regression tripwire, >10% fails) =="
python scripts/bench_trend.py

if command -v ruff >/dev/null 2>&1; then
    echo "== ruff check =="
    ruff check xllm_service_tpu tests benchmarks scripts
else
    echo "== ruff check: skipped (ruff not installed; config lives in pyproject.toml) =="
fi

echo "check.sh: OK  (tier-1 tests: scripts/tier1.sh)"
