#!/usr/bin/env bash
# Static-analysis gate: xlint (project concurrency invariants, always) +
# ruff (generic lint, when installed). CI runs the same xlint pass via
# tests/test_xlint.py::test_xlint_tree_clean. Tier-1 tests run separately
# via scripts/tier1.sh (the canonical 3-chunk split).
set -euo pipefail
cd "$(dirname "$0")/.."

# `check.sh --changed <git-ref>` scopes xlint's REPORT to files the
# diff touches (analysis still runs tree-wide; registry files are never
# filtered) — the fast pre-push loop. Everything else runs unchanged.
CHANGED_ARGS=()
if [ "${1:-}" = "--changed" ]; then
    [ -n "${2:-}" ] || { echo "check.sh: --changed takes a git ref" >&2; exit 2; }
    CHANGED_ARGS=(--changed "$2")
    shift 2
fi

# One xlint invocation per profile, consumed as --format json: stable
# exit codes (0 clean / 1 violations / 2 usage), machine-readable
# violation list, file counts from the single shared parse.
run_xlint() {
    local label="$1"; shift
    local out rc=0
    out=$(python -m xllm_service_tpu.devtools.xlint --format json \
          ${CHANGED_ARGS[@]+"${CHANGED_ARGS[@]}"} "$@") \
        || rc=$?
    if [ "$rc" -eq 0 ]; then
        echo "$out" | python -c 'import json, sys
d = json.load(sys.stdin)
scope = ", changed vs %s" % d["changed"] if d.get("changed") else ""
print("xlint: clean (%d files, %s profile%s)" % (d["files"], d["profile"], scope))'
        return 0
    fi
    echo "$out" | python -c 'import json, sys
d = json.load(sys.stdin)
for v in d["violations"]:
    print("%s:%d: [%s] %s" % (v["path"], v["line"], v["rule"], v["message"]))
print("xlint: %d violation(s)" % d["count"])' 2>/dev/null \
        || echo "$out"
    return "$rc"
}

echo "== native hot-path core (csrc/ build + loader verdict) =="
# Build is best-effort: the Makefile skips with a message when Python.h
# is absent. The loader verdict is asserted either way — a box WITH the
# toolchain must end up native-active (a silent fallback would make the
# fleet-bench A/B meaningless), while a box without it must report a
# clean pure-python fallback, never an import error.
make -C csrc libhotcore.so
python - <<'PYEOF'
import json, sysconfig, pathlib
from xllm_service_tpu.common import native
st = native.status()
print("native loader:", json.dumps(st))
so = pathlib.Path("csrc/libhotcore.so")
have_hdr = pathlib.Path(sysconfig.get_paths()["include"], "Python.h").exists()
if so.exists():
    assert st["loaded"], f"libhotcore.so built but loader inactive: {st}"
    assert all(st["components"].values()), f"partial native: {st}"
elif have_hdr:
    raise SystemExit("check.sh: Python.h present but csrc build left no "
                     ".so — build is broken, not merely unavailable")
else:
    assert not st["loaded"], f"no .so yet loader active? {st}"
    print("native loader: pure-python fallback (no toolchain) — OK")
PYEOF

echo "== xlint (concurrency + RCU + state-ownership invariants) =="
run_xlint strict xllm_service_tpu

echo "== xlint --support (tests/ + benchmarks/, relaxed profile) =="
run_xlint support --support tests benchmarks

echo "== bench trend (headline-metric regression tripwire, >10% fails) =="
python scripts/bench_trend.py

echo "== topology plane under LOCK+RCU+STATE instrumentation =="
# The placement plane touches every shared-state surface at once
# (routing snapshot, metrics census, controller census, chaos drill) —
# run its suite with all three runtime verifiers armed so a discipline
# regression fails here, not in a soak.
JAX_PLATFORMS=cpu XLLM_LOCK_DEBUG=1 XLLM_RCU_DEBUG=1 XLLM_STATE_DEBUG=1 \
    python -m pytest tests/test_topology.py -q -p no:cacheprovider

if command -v ruff >/dev/null 2>&1; then
    echo "== ruff check =="
    ruff check xllm_service_tpu tests benchmarks scripts
else
    echo "== ruff check: skipped (ruff not installed; config lives in pyproject.toml) =="
fi

echo "check.sh: OK  (tier-1 tests: scripts/tier1.sh)"
