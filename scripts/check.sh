#!/usr/bin/env bash
# Static-analysis gate: xlint (project concurrency invariants, always) +
# ruff (generic lint, when installed). CI runs the same xlint pass via
# tests/test_xlint.py::test_xlint_tree_clean.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== xlint (concurrency + RCU publication invariants) =="
python -m xllm_service_tpu.devtools.xlint xllm_service_tpu

echo "== xlint --support (tests/ + benchmarks/, relaxed profile) =="
python -m xllm_service_tpu.devtools.xlint --support tests benchmarks

echo "== bench trend (headline-metric regression tripwire, >10% fails) =="
python scripts/bench_trend.py

if command -v ruff >/dev/null 2>&1; then
    echo "== ruff check =="
    ruff check xllm_service_tpu tests benchmarks scripts
else
    echo "== ruff check: skipped (ruff not installed; config lives in pyproject.toml) =="
fi

echo "check.sh: OK"
