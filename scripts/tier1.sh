#!/usr/bin/env bash
# Tier-1 test runner: the canonical 3-chunk split.
#
# The single-process tier-1 run (`pytest tests/ -q -m 'not slow'`) takes
# ~1300s on a 2-core box and times out the 870s verify budget — every PR
# since 7 hand-rolled the same split. This script IS the split:
#
#   chunk 1  models + kernels (the XLA-compile-heavy leg)
#   chunk 2  engine + e2e service / disagg / multimaster / tiering drills
#   chunk 3  everything else (scheduler, coordination, devtools, common)
#
# Membership is pattern-based with chunk 3 as the remainder, so new test
# files are always covered; the script fails loudly if the chunks do not
# partition tests/test_*.py. Each chunk runs under its own `timeout -k
# 10 870` with the same flags as the ROADMAP's tier-1 verify line, and
# passed-test accounting is aggregated across chunks (dots counting, the
# same scheme the verify line uses).
#
# Usage: scripts/tier1.sh [1|2|3|all]        (default: all, sequential)
#   env XLLM_TIER1_TIMEOUT=<s>               per-chunk timeout (870)
set -u
cd "$(dirname "$0")/.."

WHICH="${1:-all}"
TIMEOUT="${XLLM_TIER1_TIMEOUT:-870}"

CHUNK1_PATTERNS=(
    test_models test_models_extra test_gemma test_mixtral test_qwen2_vl
    test_hf_parity test_loader test_quant test_mrope test_speculative
    test_sarathi test_seq_parallel test_pipeline test_tp_serving
    test_moe_pd test_checkpoint_serving test_pallas_attention
    test_mq_paged_attention test_cp_paged_attention test_compile_gate
    test_summarize_sweep
)
CHUNK2_PATTERNS=(
    test_engine test_e2e_epd test_e2e_ha test_e2e_pd_disagg
    test_e2e_real_engine test_e2e_routing test_e2e_service
    test_multimaster test_multiprocess_cluster test_multihost test_soak
    test_chaos_failover test_kv_tiering test_fleet_observability
    test_hybrid_scheduling test_mixed_decode_chunk
    test_chunked_multimodal test_dp_replicas test_northstar_topology
    test_pallas_engine_routing
)

in_list() {
    local needle="$1"; shift
    local x
    for x in "$@"; do [ "$x" = "$needle" ] && return 0; done
    return 1
}

chunk1=(); chunk2=(); chunk3=()
for f in tests/test_*.py; do
    base="$(basename "$f" .py)"
    if in_list "$base" "${CHUNK1_PATTERNS[@]}"; then
        chunk1+=("$f")
    elif in_list "$base" "${CHUNK2_PATTERNS[@]}"; then
        chunk2+=("$f")
    else
        chunk3+=("$f")
    fi
done

# Pattern-drift guard: every explicit CHUNK1/CHUNK2 pattern must match a
# live test file (a renamed/deleted file would silently shift its slot
# into the remainder chunk — fail loudly instead). Chunk 3 being the
# remainder of the same glob, the partition itself holds by construction.
for base in "${CHUNK1_PATTERNS[@]}" "${CHUNK2_PATTERNS[@]}"; do
    if [ ! -f "tests/$base.py" ]; then
        echo "tier1.sh: chunk pattern '$base' matches no tests/$base.py" \
             "(stale pattern — update the chunk lists)" >&2
        exit 2
    fi
done

run_chunk() {
    local n="$1"; shift
    local log="/tmp/_t1_chunk$n.log"
    rm -f "$log"
    echo "=== tier-1 chunk $n ($# files, timeout ${TIMEOUT}s) ==="
    set -o pipefail
    timeout -k 10 "$TIMEOUT" env JAX_PLATFORMS=cpu \
        python -m pytest "$@" -q -m 'not slow' \
        --continue-on-collection-errors -p no:cacheprovider \
        -p no:xdist -p no:randomly 2>&1 | tee "$log"
    local rc=${PIPESTATUS[0]}
    local dots
    dots=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$log" | tr -cd . | wc -c)
    echo "chunk $n: DOTS_PASSED=$dots rc=$rc"
    TOTAL_DOTS=$((TOTAL_DOTS + dots))
    [ "$rc" -ne 0 ] && FAILED_CHUNKS+=("$n(rc=$rc)")
    return 0
}

# Pure-python fallback drill: the wire/ownership/native differential
# suites run a second time with XLLM_NATIVE=0 forced, proving the
# mandatory fallbacks carry the same behavior a no-toolchain box gets.
# Rides after chunk 3; its dots are not added to TOTAL_DOTS (they would
# double-count tests the normal chunks already ran).
PURE_FILES=(tests/test_native_hotcore.py tests/test_dispatch_wire.py
            tests/test_multimaster.py)
run_pure_drill() {
    local log="/tmp/_t1_pure.log"
    rm -f "$log"
    echo "=== tier-1 pure-fallback drill (XLLM_NATIVE=0," \
         "${#PURE_FILES[@]} files) ==="
    set -o pipefail
    timeout -k 10 "$TIMEOUT" env JAX_PLATFORMS=cpu XLLM_NATIVE=0 \
        python -m pytest "${PURE_FILES[@]}" -q -m 'not slow' \
        --continue-on-collection-errors -p no:cacheprovider \
        -p no:xdist -p no:randomly 2>&1 | tee "$log"
    local rc=${PIPESTATUS[0]}
    echo "pure drill: rc=$rc"
    [ "$rc" -ne 0 ] && FAILED_CHUNKS+=("pure(rc=$rc)")
    return 0
}

TOTAL_DOTS=0
FAILED_CHUNKS=()
case "$WHICH" in
    1) run_chunk 1 "${chunk1[@]}" ;;
    2) run_chunk 2 "${chunk2[@]}" ;;
    3) run_chunk 3 "${chunk3[@]}"; run_pure_drill ;;
    all)
        run_chunk 1 "${chunk1[@]}"
        run_chunk 2 "${chunk2[@]}"
        run_chunk 3 "${chunk3[@]}"
        run_pure_drill
        ;;
    *) echo "usage: scripts/tier1.sh [1|2|3|all]" >&2; exit 2 ;;
esac

echo
echo "tier1.sh: TOTAL DOTS_PASSED=$TOTAL_DOTS"
if [ "${#FAILED_CHUNKS[@]}" -gt 0 ]; then
    echo "tier1.sh: non-zero chunk exits: ${FAILED_CHUNKS[*]} (inspect" \
         "/tmp/_t1_chunk*.log — the known container-limitation failures" \
         "exit 1 too)"
    exit 1
fi
