#!/usr/bin/env python
"""Bench-trend tripwire: fail when the newest recorded benchmark round
regresses its family's tracked headline metric by more than 10%.

The repo records one ``BENCH_<family>_r<NN>.json`` artifact per perf
round (hotpath, kvcache, kvtier, multimaster, tracing, ...). Each family
has a few *headline* metrics — the numbers quoted in
``docs/performance.md`` — and a silent regression there is exactly the
kind of drift a later PR ships by accident. This script:

1. groups the ``BENCH_*.json`` artifacts by family,
2. for every family with >= 2 rounds, compares the newest round's
   tracked metrics against the previous round's,
3. exits non-zero when any tracked metric regressed past the threshold
   (default 10) in its bad direction (lower for throughput/speedups,
   higher for latencies). Metrics that are already percentages
   (``*_pct``/``*_perc`` — overhead ratios, step deltas) are judged in
   ABSOLUTE percentage points, not relative change: their baselines sit
   at the noise floor near 0, where relative math is meaningless.

Tracked metrics are dotted JSON paths per family (``TRACKED`` below);
families may also self-describe by shipping a top-level ``"headline"``
object — every numeric leaf under it is auto-tracked, direction inferred
from the key name (``*_ms``/``*_seconds`` regress upward, everything
else downward). Missing paths are skipped with a note (schemas evolve);
a missing FAMILY is never an error.

Wired into ``scripts/check.sh``; ``--list`` prints what would be
compared without judging.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from pathlib import Path
from typing import Any, Iterator, Optional

#: family -> [(dotted path, higher_is_better)]
TRACKED: dict[str, list[tuple[str, bool]]] = {
    "hotpath": [
        ("headline.sustained_req_per_s_conc8.after", True),
        ("headline.ttft_p50_at_equal_offered_load_6p5rps_ms.after", False),
    ],
    "kvcache": [
        ("index.match_new.throughput_1t_per_s", True),
        ("index.match_new.throughput_4t_per_s", True),
        ("hashing.new_us_per_prompt", False),
        ("routed_ttft.CAR.req_per_s", True),
    ],
    "kvtier": [
        ("tier_ttft.warm_vs_cold_speedup", True),
        ("capacity.capacity_multiplier", True),
        ("step_latency.delta_p50_perc", False),
    ],
    "tracing": [
        ("headline.ring_overhead_p50_pct", False),
        ("headline.sampled_overhead_p50_pct", False),
    ],
    "leakcheck": [
        ("headline.leak_overhead_pct", False),
        ("headline.combined_overhead_pct", False),
    ],
    "profile": [
        ("headline.profile_overhead_pct", False),
    ],
    "fleet": [
        ("headline.native_route_stream_speedup", True),
        ("headline.route_stream_cpu_us_per_req", False),
        ("headline.agg_rps_masters_4", True),
        ("headline.masters_4_over_1_scaling", True),
    ],
    "topo": [
        ("headline.topo_ttft_p50_speedup", True),
        ("headline.same_slice_pair_share", True),
        ("headline.topo_handoff_p95_ms", False),
    ],
}

_NAME_RE = re.compile(r"^BENCH_(?:([a-z0-9]+)_)?r(\d+)\.json$")

#: Key suffixes whose headline values regress UPWARD (latencies, costs).
_LOWER_IS_BETTER_SUFFIXES = ("_ms", "_us", "_ns", "_seconds", "_pct",
                             "_perc")


def _lookup(obj: Any, path: str) -> Optional[float]:
    for part in path.split("."):
        if not isinstance(obj, dict) or part not in obj:
            return None
        obj = obj[part]
    if isinstance(obj, bool) or not isinstance(obj, (int, float)):
        return None
    return float(obj)


def _headline_paths(doc: dict) -> Iterator[tuple[str, bool]]:
    """Auto-tracked numeric leaves under a top-level "headline" object."""
    def walk(obj: Any, prefix: str) -> Iterator[tuple[str, bool]]:
        if isinstance(obj, dict):
            for k, v in obj.items():
                yield from walk(v, f"{prefix}.{k}")
        elif isinstance(obj, (int, float)) and not isinstance(obj, bool):
            leaf = prefix.rsplit(".", 1)[-1]
            higher = not leaf.endswith(_LOWER_IS_BETTER_SUFFIXES)
            yield prefix, higher

    if isinstance(doc.get("headline"), dict):
        yield from walk(doc["headline"], "headline")


def families(root: Path) -> dict[str, list[tuple[int, Path]]]:
    out: dict[str, list[tuple[int, Path]]] = {}
    for p in sorted(root.glob("BENCH_*.json")):
        m = _NAME_RE.match(p.name)
        if not m or m.group(1) is None:
            continue   # seed BENCH_rNN.json artifacts carry no metrics
        out.setdefault(m.group(1), []).append((int(m.group(2)), p))
    for rounds in out.values():
        rounds.sort()
    return out


#: Composition-shift flags: a frame newly holding more than this share
#: of profile samples (or whose share grew by more than this many
#: points) between rounds is a flagged shift — the hot-frame evidence
#: ROADMAP's native-extension item reads. Informational, not a failure:
#: composition moves for good reasons too (a fix shrinks a tower).
COMPOSITION_SHIFT_POINTS = 10.0


def _composition_shifts(prev: dict, new: dict) -> list[str]:
    """Diff ``composition.profile_top_frames`` (profile family docs):
    per-frame self-sample share, new round vs previous."""
    def shares(doc: dict) -> dict[str, float]:
        frames = (doc.get("composition") or {}).get("profile_top_frames")
        return {f["frame"]: float(f.get("pct", 0.0))
                for f in frames or () if isinstance(f, dict)}

    a, b = shares(prev), shares(new)
    if not a or not b:
        return []
    out = []
    for frame, pct in sorted(b.items(), key=lambda kv: -kv[1]):
        delta = pct - a.get(frame, 0.0)
        if delta > COMPOSITION_SHIFT_POINTS:
            was = a.get(frame)
            out.append(f"{frame}: {pct:.1f}% of samples "
                       f"({'new' if was is None else f'was {was:.1f}%'}, "
                       f"{delta:+.1f} points)")
    return out


def compare(root: Path, threshold_pct: float = 10.0,
            list_only: bool = False) -> int:
    regressions: list[str] = []
    compared = 0
    for family, rounds in sorted(families(root).items()):
        if len(rounds) < 2:
            print(f"bench_trend: {family}: only round r{rounds[-1][0]:02d} "
                  f"recorded; nothing to diff")
            continue
        (prev_r, prev_p), (new_r, new_p) = rounds[-2], rounds[-1]
        try:
            prev = json.loads(prev_p.read_text())
            new = json.loads(new_p.read_text())
        except (OSError, json.JSONDecodeError) as e:
            print(f"bench_trend: {family}: unreadable artifact ({e}); "
                  f"skipping")
            continue
        tracked = dict(TRACKED.get(family, ()))
        for path, higher in _headline_paths(new):
            tracked.setdefault(path, higher)
        for shift in _composition_shifts(prev, new):
            print(f"bench_trend: {family}: COMPOSITION SHIFT — {shift}")
        for path, higher in sorted(tracked.items()):
            a, b = _lookup(prev, path), _lookup(new, path)
            if a is None or b is None:
                print(f"bench_trend: {family}.{path}: absent in "
                      f"r{prev_r:02d} or r{new_r:02d}; skipping")
                continue
            compared += 1
            leaf = path.rsplit(".", 1)[-1]
            if leaf.endswith(("_pct", "_perc")):
                # Already-a-percentage metrics (e.g. tracing overhead,
                # decode-step delta) compare in absolute points: their
                # baselines sit at the noise floor (~0), where relative
                # change is meaningless — and a 0 baseline must not
                # silently disarm the tripwire.
                delta = b - a
                regressed_pct = -delta if higher else delta
                shown = f"{delta:+.2f} points"
            elif a == 0:
                print(f"bench_trend: {family}.{path}: zero baseline in "
                      f"r{prev_r:02d}; cannot judge relative change — "
                      f"skipping (non-pct metric)")
                continue
            else:
                delta_pct = (b - a) / abs(a) * 100.0
                regressed_pct = -delta_pct if higher else delta_pct
                shown = f"{delta_pct:+.1f}%"
            arrow = "better" if regressed_pct < 0 else "worse"
            line = (f"{family}.{path}: r{prev_r:02d}={a:g} -> "
                    f"r{new_r:02d}={b:g} ({shown}, {arrow})")
            if list_only:
                print("bench_trend:", line)
                continue
            if regressed_pct > threshold_pct:
                regressions.append(line)
            else:
                print("bench_trend: ok:", line)
    if regressions:
        print(f"\nbench_trend: FAIL — headline metric regression(s) over "
              f"{threshold_pct:g}%:")
        for line in regressions:
            print("  " + line)
        return 1
    print(f"bench_trend: OK ({compared} tracked metric(s) compared)")
    return 0


def main(argv: Optional[list[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--root", default=str(Path(__file__).resolve()
                                         .parent.parent),
                   help="directory holding the BENCH_*.json artifacts")
    p.add_argument("--threshold-pct", type=float, default=10.0)
    p.add_argument("--list", action="store_true", dest="list_only",
                   help="print comparisons without judging")
    args = p.parse_args(argv)
    return compare(Path(args.root), args.threshold_pct, args.list_only)


if __name__ == "__main__":
    sys.exit(main())
