#!/usr/bin/env bash
# Chaos soak: run the fault-plane drills in a loop with randomized seeds
# and report the pass rate.
#
# The drills themselves are deterministic per seed (the fault plane draws
# all randomness from one seeded RNG), so any failing iteration can be
# replayed exactly with:   XLLM_CHAOS_SEED=<seed> pytest -m chaos
#
# Usage: scripts/chaos_soak.sh [iterations] [extra pytest args...]
set -u

ITERS="${1:-20}"
shift 2>/dev/null || true
cd "$(dirname "$0")/.."

pass=0
fail=0
failed_seeds=()
for i in $(seq 1 "$ITERS"); do
    seed=$((RANDOM * 32768 + RANDOM))
    echo "=== chaos iteration $i/$ITERS (seed=$seed) ==="
    if JAX_PLATFORMS=cpu XLLM_CHAOS_SEED=$seed \
        python -m pytest tests/test_chaos_failover.py -q -m chaos \
        -p no:cacheprovider "$@"; then
        pass=$((pass + 1))
    else
        fail=$((fail + 1))
        failed_seeds+=("$seed")
    fi
done

echo
echo "chaos soak: $pass/$ITERS passed"
if [ "$fail" -gt 0 ]; then
    echo "failing seeds (replay with XLLM_CHAOS_SEED=<seed>): ${failed_seeds[*]}"
    exit 1
fi
