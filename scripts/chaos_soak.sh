#!/usr/bin/env bash
# Chaos soak: run the fault-plane drills in a loop with randomized seeds
# and report the pass rate.
#
# The drills themselves are deterministic per seed (the fault plane draws
# all randomness from one seeded RNG), so any failing iteration can be
# replayed exactly with:   XLLM_CHAOS_SEED=<seed> pytest -m chaos
#
# Usage: scripts/chaos_soak.sh [iterations] [--masters|--tier|--obs|--state|--autoscale|--overload|--outage|--profile] [extra pytest args...]
#   --masters   soak the multi-master plane drills (tests/test_multimaster.py:
#               owner/master kill mid-stream, split-brain demotion, write-lease
#               proxying) instead of the single-master failover drills.
#   --tier      soak the tiered KV-cache churn drills (tests/test_kv_tiering.py:
#               eviction→offload→onload round trips under a saturated pump,
#               streamed PD handoff with faults injected at the
#               kv_transfer.offer / kv_transfer.pull points → inline fallback).
#   --obs       soak the fleet-observability drills
#               (tests/test_fleet_observability.py: fleet-trace merge across
#               frontends+engines under a mid-stream engine kill, dead-agent
#               partial-result markers, and the owner-kill drill asserting the
#               anomaly flight recorder captured the recovery).
#   --state     soak the state-ownership verifier drills
#               (tests/test_state_debug.py: a deliberate unguarded
#               cross-thread write must be caught, and a heartbeat storm
#               against a churning fleet must record no discipline
#               violations).
#   --autoscale soak the closed-loop autoscaler drills
#               (tests/test_autoscaler.py: instance killed mid-burst is
#               failed over AND replaced through the actuator, a
#               DRAINING instance killed mid-drain falls back to the
#               normal failover path, graceful drains retire without an
#               eviction alarm).
#   --overload  soak the overload-hardening drills (tests/
#               test_overload.py: deadline expiry mid-decode stops the
#               engine within one pump, shed-under-burst keeps admitted
#               requests whole, circuit-breaker open/probe/restore, the
#               relayed client-disconnect cancellation drill, retry-
#               budget exhaustion).
#   --profile   soak the continuous-profiling drills
#               (tests/test_profiling.py TestFleetProfile: the
#               always-on sampler stays up through a fleet-scope
#               /admin/profile merge with a killed agent, the relayed
#               failed-over request's critical path sums to the
#               measured TTFT, and SLO-breach bundles carry a profile
#               window — with the sampler thread itself running under
#               every instrumented leg below, including the combined
#               LOCK+RCU+STATE+LEAK one).
#   --outage    soak the coordination-plane static-stability drills
#               (tests/test_multimaster.py TestCoordinationOutage +
#               tests/test_chaos_failover.py TestCoordinationOutageFailover:
#               total coordination outage mid-stream over the real TCP
#               wire, census freeze / sticky mastership / held-action
#               replay, fencing demotion, and an engine crash DURING
#               the outage failing over byte-identically).
#
# After the randomized-seed loop, the INSTRUMENTED legs run (one
# iteration each, counted in the pass rate): XLLM_LOCK_DEBUG=1 (the
# lock-order/hold race detector), XLLM_RCU_DEBUG=1 (the snapshot
# deep-freeze race detector), XLLM_STATE_DEBUG=1 (the shared-state
# ownership / attribute-race verifier — any write violating its declared
# discipline fails the drill), XLLM_LEAK_DEBUG=1 (the paired-effect
# leak verifier — double-releases, strict-pair leaks and metric-series
# resurrections fail the drill), and all four combined as a smoke. Set
# XLLM_SOAK_SKIP_DEBUG_LEGS=1 to run the plain loop only.
set -u

ITERS="${1:-20}"
shift 2>/dev/null || true
SUITES=("tests/test_chaos_failover.py")
KARGS=()
if [ "${1:-}" = "--masters" ]; then
    SUITES=("tests/test_multimaster.py")
    shift
elif [ "${1:-}" = "--tier" ]; then
    SUITES=("tests/test_kv_tiering.py")
    shift
elif [ "${1:-}" = "--obs" ]; then
    SUITES=("tests/test_fleet_observability.py")
    shift
elif [ "${1:-}" = "--state" ]; then
    SUITES=("tests/test_state_debug.py")
    shift
elif [ "${1:-}" = "--autoscale" ]; then
    SUITES=("tests/test_autoscaler.py")
    shift
elif [ "${1:-}" = "--overload" ]; then
    SUITES=("tests/test_overload.py")
    shift
elif [ "${1:-}" = "--profile" ]; then
    SUITES=("tests/test_profiling.py")
    shift
elif [ "${1:-}" = "--outage" ]; then
    SUITES=("tests/test_multimaster.py" "tests/test_chaos_failover.py")
    KARGS=(-k "CoordinationOutage")
    shift
fi
cd "$(dirname "$0")/.."

pass=0
fail=0
failed_seeds=()
for i in $(seq 1 "$ITERS"); do
    seed=$((RANDOM * 32768 + RANDOM))
    echo "=== chaos iteration $i/$ITERS (seed=$seed, suite=${SUITES[*]}) ==="
    if JAX_PLATFORMS=cpu XLLM_CHAOS_SEED=$seed \
        python -m pytest "${SUITES[@]}" -q -m chaos \
        -p no:cacheprovider ${KARGS[@]+"${KARGS[@]}"} "$@"; then
        pass=$((pass + 1))
    else
        fail=$((fail + 1))
        failed_seeds+=("$seed")
    fi
done

total="$ITERS"
if [ "${XLLM_SOAK_SKIP_DEBUG_LEGS:-}" != "1" ]; then
    for leg in "XLLM_LOCK_DEBUG=1" "XLLM_RCU_DEBUG=1" \
               "XLLM_STATE_DEBUG=1" "XLLM_LEAK_DEBUG=1" \
               "XLLM_LOCK_DEBUG=1 XLLM_RCU_DEBUG=1 XLLM_STATE_DEBUG=1 XLLM_LEAK_DEBUG=1"; do
        seed=$((RANDOM * 32768 + RANDOM))
        total=$((total + 1))
        echo "=== instrumented leg: $leg (seed=$seed, suite=${SUITES[*]}) ==="
        if JAX_PLATFORMS=cpu XLLM_CHAOS_SEED=$seed \
            env $leg python -m pytest "${SUITES[@]}" -q -m chaos \
            -p no:cacheprovider ${KARGS[@]+"${KARGS[@]}"} "$@"; then
            pass=$((pass + 1))
        else
            fail=$((fail + 1))
            failed_seeds+=("$seed($leg)")
        fi
    done
fi

echo
echo "chaos soak: $pass/$total passed"
if [ "$fail" -gt 0 ]; then
    echo "failing seeds (replay with XLLM_CHAOS_SEED=<seed>): ${failed_seeds[*]}"
    exit 1
fi
