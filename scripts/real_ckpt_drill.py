"""Real-checkpoint end-to-end drill (VERDICT r4 next #2): load a REAL
published HF checkpoint through models/hf_config + models/loader, serve
it through the FULL stack (HTTP client → master → engine agent →
engine), and assert the served greedy continuation token-matches
`transformers` greedy generation on the same weights.

    python scripts/real_ckpt_drill.py [--ckpt DIR] [--tokens N]

Checkpoint resolution, in order:
  1. --ckpt / XLLM_REAL_CKPT (a local HF model directory);
  2. huggingface_hub.snapshot_download(XLLM_REAL_CKPT_REPO, default
     Qwen/Qwen2.5-0.5B) — attempted with a deadline; in a zero-egress
     sandbox this fails fast and the drill records the attempt.

Emits ONE JSON line either way:

    {"metric": "real_ckpt_parity", "backend": ..., "ok": true,
     "model_type": "qwen2", "tokens_matched": 32, "tokens_total": 32}
    {"metric": "real_ckpt_parity", "backend": ...,
     "skipped": "checkpoint unavailable: ..."}

`skipped` (not `error`) keeps the sweep loop from treating a missing
network as a bench failure; a real parity MISMATCH sets ok=false AND
`error`, which the sweep surfaces.

The hermetic test (tests/test_hf_parity.py) drives run_drill() on
synthetic checkpoints, so the full machinery — config mapping, loader,
serve stack, transformers comparison — is CPU-proven even while the
sandbox has no network; pointing it at a real dir exercises the
identical path.

Reference analog: the reference boots its fleet straight from HF model
dirs (`docs/en/getting_started.md:73-90`).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

DEFAULT_REPO = "Qwen/Qwen2.5-0.5B"
PROMPT = "The capital of France is"


def resolve_checkpoint(explicit: str | None) -> tuple[str | None, str]:
    """Return (ckpt_dir, note). ckpt_dir None = unavailable."""
    cand = explicit or os.environ.get("XLLM_REAL_CKPT", "")
    if cand:
        if (Path(cand) / "config.json").exists():
            return cand, f"local dir {cand}"
        return None, f"XLLM_REAL_CKPT={cand} has no config.json"
    repo = os.environ.get("XLLM_REAL_CKPT_REPO", DEFAULT_REPO)
    # Hard deadline around the whole download: hub retry/DNS stalls can
    # far exceed etag_timeout in a zero-egress sandbox, and the sweep
    # step must record "skipped", not hang into its kill timeout.
    deadline_s = float(os.environ.get("XLLM_CKPT_DOWNLOAD_DEADLINE_S",
                                      "600"))
    import threading
    box: dict = {}

    def _download():
        try:
            from huggingface_hub import snapshot_download
            box["dir"] = snapshot_download(repo, etag_timeout=10)
        except Exception as e:  # noqa: BLE001 — zero-egress sandbox
            box["err"] = f"{type(e).__name__}: {e}"[:250]

    # Daemon thread: an abandoned stalled download must not block
    # process exit after the skipped line prints.
    t = threading.Thread(target=_download, daemon=True)
    t.start()
    t.join(timeout=deadline_s)
    if "dir" in box:
        return box["dir"], f"downloaded {repo}"
    if "err" in box:
        return None, (f"checkpoint unavailable: download of {repo} "
                      f"failed ({box['err']})")
    return None, (f"checkpoint unavailable: download of {repo} hit "
                  f"the {deadline_s:.0f}s deadline")


def hf_greedy_ids(ckpt_dir: str, prompt_ids: list[int],
                  max_new: int) -> list[int]:
    """transformers greedy continuation (float32, EOS disabled so the
    comparison covers exactly max_new tokens)."""
    import torch
    from transformers import AutoModelForCausalLM

    model = AutoModelForCausalLM.from_pretrained(
        ckpt_dir, torch_dtype=torch.float32)
    model.eval()
    with torch.no_grad():
        out = model.generate(
            torch.tensor([prompt_ids]), max_new_tokens=max_new,
            do_sample=False, eos_token_id=None, pad_token_id=0)
    return out[0, len(prompt_ids):].tolist()


def run_drill(ckpt_dir: str, prompt: str = PROMPT, max_new: int = 32,
              max_context: int = 1024) -> dict:
    """Serve `ckpt_dir` through the full stack and compare the greedy
    continuation against transformers. Importable — the hermetic test
    runs this exact function on synthetic checkpoints."""
    import jax.numpy as jnp
    import requests

    from xllm_service_tpu.common.config import ServiceOptions
    from xllm_service_tpu.common.types import InstanceType
    from xllm_service_tpu.coordination.memory import (InMemoryCoordination,
                                                      MemoryStore)
    from xllm_service_tpu.engine.agent import AgentConfig, EngineAgent
    from xllm_service_tpu.engine.config import EngineConfig
    from xllm_service_tpu.master import Master
    from xllm_service_tpu.models.hf_config import (load_checkpoint,
                                                   model_config_from_hf)
    from xllm_service_tpu.tokenizer import TokenizerFactory

    import jax

    backend = jax.default_backend()
    tok = TokenizerFactory.create_tokenizer(str(ckpt_dir))
    prompt_ids = tok.encode(prompt)
    # transformers reference FIRST: the torch model frees before the JAX
    # param tree materializes, halving peak host RAM (both are float32
    # full copies of the checkpoint).
    hf_ids = hf_greedy_ids(ckpt_dir, prompt_ids, max_new)

    # float32 end to end, and matmuls pinned to true-f32 accumulation:
    # on TPU the default precision runs f32 matmuls as bf16 passes,
    # which can flip greedy near-ties vs transformers' float32 CPU math.
    prev_prec = jax.config.jax_default_matmul_precision
    jax.config.update("jax_default_matmul_precision", "highest")
    cfg = model_config_from_hf(ckpt_dir, dtype=jnp.float32,
                               max_context_len=max_context)
    params = load_checkpoint(ckpt_dir, cfg)

    store = MemoryStore(expiry_tick_s=0.05)
    opts = ServiceOptions(host="127.0.0.1", http_port=0, rpc_port=0,
                          lease_ttl_s=2.0, sync_interval_s=0.3,
                          reconcile_interval_s=0.1,
                          tokenizer_path=str(ckpt_dir))
    master = Master(opts, coord=InMemoryCoordination(store))
    master.start()
    agent = None
    try:
        model_id = Path(ckpt_dir).name or "real-ckpt"
        # Page-aligned shapes (EngineConfig.validate): one bucket that
        # fits the prompt, a max_seq that fits prompt+continuation.
        align = 16
        b1 = max(32, -(-len(prompt_ids) // align) * align)
        max_seq = min(cfg.max_context_len,
                      max(256, b1 + -(-max_new // align) * align + align))
        ecfg = EngineConfig(
            model_id=model_id, model=cfg, model_family=cfg.name,
            num_pages=2 * max_seq // align + 32, page_size=align,
            hash_block_size=32, max_batch_size=2,
            max_seq_len=max_seq,
            prefill_buckets=(b1, max_seq) if b1 < max_seq else (max_seq,))
        agent = EngineAgent(
            ecfg,
            AgentConfig(host="127.0.0.1", model_id=model_id,
                        instance_type=InstanceType.MIX,
                        tokenizer_path=str(ckpt_dir),
                        heartbeat_interval_s=0.3, lease_ttl_s=2.0),
            coord=InMemoryCoordination(store), params=params)
        agent.start()

        import time
        deadline = time.time() + 60
        while time.time() < deadline:
            if master.scheduler.instance_mgr.get_instance_meta(agent.name):
                break
            time.sleep(0.1)
        else:
            raise TimeoutError("engine instance never registered")

        r = requests.post(
            f"http://127.0.0.1:{master.http_port}/v1/completions",
            json={"model": model_id, "prompt": prompt,
                  "max_tokens": max_new, "temperature": 0,
                  "ignore_eos": True},
            timeout=600)
        r.raise_for_status()
        served_text = r.json()["choices"][0]["text"]
    finally:
        if agent is not None:
            agent.stop()
        master.stop()
        store.close()
        jax.config.update("jax_default_matmul_precision", prev_prec)

    # Both sides decode through the SAME tokenizer: equal ids ⇒ equal
    # text, and a text mismatch pinpoints the first diverging token.
    hf_text = tok.decode(hf_ids)
    matched = 0
    for i in range(1, len(hf_ids) + 1):
        if served_text.startswith(tok.decode(hf_ids[:i])):
            matched = i
    ok = served_text == hf_text
    out = {"metric": "real_ckpt_parity", "backend": backend, "ok": ok,
           "model_type": cfg.name, "tokens_total": len(hf_ids),
           "tokens_matched": matched,
           "prompt_tokens": len(prompt_ids)}
    if not ok:
        out["error"] = (f"greedy divergence after {matched}/{len(hf_ids)} "
                        f"tokens: served={served_text[:120]!r} "
                        f"hf={hf_text[:120]!r}")
    return out


def _backend() -> str:
    """First jax touch, guarded the way bench.py guards it: a dead
    remote-TPU relay makes in-process first init hang far past any
    timeout, so probe in a subprocess and pin CPU before importing."""
    import bench

    if os.environ.get("JAX_PLATFORMS") == "cpu" or not bench._accel_alive():
        bench._pin_cpu()
        import jax
        jax.config.update("jax_platforms", "cpu")
        return jax.default_backend()
    import jax
    return jax.default_backend()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ckpt", default=None)
    ap.add_argument("--tokens", type=int, default=32)
    ap.add_argument("--prompt", default=PROMPT)
    args = ap.parse_args()

    backend = _backend()
    ckpt, note = resolve_checkpoint(args.ckpt)
    if ckpt is None:
        print(json.dumps({"metric": "real_ckpt_parity",
                          "backend": backend, "skipped": note}))
        return
    try:
        result = run_drill(ckpt, prompt=args.prompt, max_new=args.tokens)
        result["checkpoint"] = note
    except Exception as e:  # noqa: BLE001 — one-JSON-line contract
        result = {"metric": "real_ckpt_parity", "backend": backend,
                  "ok": False, "checkpoint": note,
                  "error": f"{type(e).__name__}: {e}"[:400]}
    print(json.dumps(result))


if __name__ == "__main__":
    main()
